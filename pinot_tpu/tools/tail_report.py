"""Tail-latency attribution report: where do the slow queries spend it?

Input is a ``/debug/tails`` payload (utils/tailsample.py) — fetched
live from a broker, read from a saved JSON file, or dug out of a
doctor / flight-recorder bundle — rendered as a per-plan-shape
attribution table:

    digest    table      tails  p50ms   p99ms  top phase (share)
    783f0726  testTable     41  212.4   480.1  laneWait (70.2%)
        shape: SELECT sum(..) FROM .. GROUP BY ..
        attribution: laneWait 70.2% | staging 21.4% | planExec 5.1% ...

Phase shares are SELF-time fractions over the retained-tail window
(a span's ms minus its children's — nesting never double-counts), so
"for this shape, tail p99 is 70% laneWait" reads straight off the
table.  The retained-entry list at the bottom links each tail back to
its requestId for ``/debug/queries`` cross-navigation.

Usage:
  python -m pinot_tpu.tools.tail_report --broker http://127.0.0.1:8099
  python -m pinot_tpu.tools.tail_report tails.json
  python -m pinot_tpu.tools.doctor http://127.0.0.1:9000 --out b.json &&
      python -m pinot_tpu.tools.tail_report b.json
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def _find_tails_payloads(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Accept a bare ``/debug/tails`` payload, a doctor bundle, or a
    flight-recorder bundle — returns every tails payload found."""
    if "byDigest" in doc or "entries" in doc:
        return [doc]
    out: List[Dict[str, Any]] = []
    # doctor bundle: instances.<name>.endpoints["/debug/tails?..."]
    for entry in (doc.get("instances") or {}).values():
        for ep, payload in (entry.get("endpoints") or {}).items():
            if ep.startswith("/debug/tails") and isinstance(payload, dict):
                if "byDigest" in payload or "entries" in payload:
                    out.append(payload)
    # flight-recorder bundle: sources.tails
    tails = (doc.get("sources") or {}).get("tails")
    if isinstance(tails, dict) and ("byDigest" in tails or "entries" in tails):
        out.append(tails)
    return out


def _merge(payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Concatenate rings / aggregates from multiple brokers (aggregates
    stay per-(broker, digest): windows are broker-local percentiles and
    cannot be merged exactly, so they are listed, not summed)."""
    merged: Dict[str, Any] = {
        "observed": 0,
        "retained": 0,
        "entries": [],
        "byDigest": [],
    }
    for p in payloads:
        merged["observed"] += int(p.get("observed") or 0)
        merged["retained"] += int(p.get("retained") or 0)
        merged["entries"].extend(p.get("entries") or [])
        merged["byDigest"].extend(p.get("byDigest") or [])
    merged["entries"].sort(key=lambda e: -(e.get("ts") or 0))
    merged["byDigest"].sort(
        key=lambda a: -((a.get("latencyMs") or {}).get("p99") or 0)
    )
    return merged


def render_report(
    tails: Dict[str, Any], top: int = 20, entries: int = 10
) -> str:
    """Tails payload -> multi-line report (pure; unit-testable)."""
    lines: List[str] = []
    lines.append(
        f"tail-based sampling: {tails.get('retained', 0)} retained of "
        f"{tails.get('observed', 0)} observed"
        + (
            f" (slowMs={tails['slowMs']:g}, 1-in-{tails.get('sampleN')})"
            if "slowMs" in tails
            else ""
        )
    )
    aggs = (tails.get("byDigest") or [])[: max(1, top)]
    if not aggs:
        lines.append("(no retained tails — nothing slow, failed, or sampled yet)")
        return "\n".join(lines) + "\n"
    lines.append("")
    lines.append(
        f"{'digest':<18} {'table':<20} {'tails':>5} {'p50ms':>9} "
        f"{'p99ms':>9}  top phase (share)"
    )
    for a in aggs:
        lat = a.get("latencyMs") or {}
        attribution = a.get("attribution") or {}
        topk = next(iter(attribution), None)
        top_str = (
            f"{topk} ({100.0 * attribution[topk]:.1f}%)" if topk else "-"
        )
        lines.append(
            f"{(a.get('digest') or '?')[:16]:<18} "
            f"{(a.get('table') or '')[:20]:<20} "
            f"{a.get('tails', 0):>5} "
            f"{lat.get('p50', 0):>9.1f} {lat.get('p99', 0):>9.1f}  {top_str}"
        )
        if a.get("summary"):
            lines.append(f"    shape: {a['summary'][:100]}")
        if attribution:
            parts = " | ".join(
                f"{k} {100.0 * v:.1f}%" for k, v in list(attribution.items())[:6]
            )
            lines.append(f"    attribution: {parts}")
    ring = (tails.get("entries") or [])[: max(0, entries)]
    if ring:
        lines.append("")
        lines.append("recent retained tails (newest first):")
        for e in ring:
            lines.append(
                f"  {e.get('requestId', '?'):<28} {e.get('reason', '?'):<8} "
                f"{e.get('timeUsedMs', 0):>9.1f}ms  "
                f"{(e.get('table') or '')[:20]:<20} "
                f"digest={str(e.get('planDigest') or '')[:16]}"
            )
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="pinot_tpu-tail-report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "source", nargs="?",
        help="tails JSON / doctor bundle / flight-recorder bundle "
        "(file or - for stdin); or use --broker",
    )
    p.add_argument("--broker", help="fetch live from this broker base URL")
    p.add_argument("--top", type=int, default=20, help="plan shapes shown")
    p.add_argument("--entries", type=int, default=10, help="ring entries shown")
    args = p.parse_args(argv)

    if args.broker:
        import urllib.request

        with urllib.request.urlopen(
            args.broker.rstrip("/") + "/debug/tails?top=1024", timeout=10
        ) as r:
            doc = json.loads(r.read())
    elif args.source:
        text = (
            sys.stdin.read() if args.source == "-" else open(args.source).read()
        )
        doc = json.loads(text)
    else:
        p.error("need a source file or --broker")
        return 2
    payloads = _find_tails_payloads(doc)
    if not payloads:
        print("no /debug/tails payload found in input", file=sys.stderr)
        return 1
    tails = payloads[0] if len(payloads) == 1 else _merge(payloads)
    sys.stdout.write(render_report(tails, top=args.top, entries=args.entries))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
