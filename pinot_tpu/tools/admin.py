"""Admin CLI — the ``PinotAdministrator`` analog (pinot-tools, 30+
commands).  Usage: ``python -m pinot_tpu.tools.admin <command> [args]``.

Commands:
  Quickstart            offline baseballStats demo (Quickstart.java:33)
  RealtimeQuickstart    streaming meetupRsvp demo
  HybridQuickstart      offline history + live stream, one logical table
  NetworkRealtimeQuickstart  same, across real processes + TCP stream broker
  StartCluster          in-process cluster with HTTP broker+controller
  StartController       standalone controller process (networked cluster)
  StartServer           standalone server process joining a controller
  StartBroker           standalone broker process joining a controller
  StartStreamBroker     standalone TCP stream broker (realtime ingest)
  CreateSegment         build a segment from CSV/JSONL + schema JSON
  UploadSegment         POST a segment file to a controller
  AddSchema / AddTable  controller CRUD
  PostQuery             run PQL against a broker
  QueryRunner           perf modes singleThread/multiThreads/targetQPS
  ShowSegment           print a segment file's metadata
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def cmd_quickstart(args) -> None:
    from pinot_tpu.tools.quickstart import run_offline_quickstart

    cluster = run_offline_quickstart(
        num_rows=args.rows, startree=args.startree, http=not args.no_http
    )
    if not args.no_http:
        print("Ctrl-C to exit.")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            cluster.stop()


def cmd_network_realtime_quickstart(args) -> None:
    from pinot_tpu.tools.quickstart import run_network_realtime_quickstart

    count = run_network_realtime_quickstart(
        num_events=args.events,
        consumer_type=args.consumer_type,
        stream_protocol=args.stream_protocol,
    )
    print(f"\nDONE networked realtime quickstart ({args.consumer_type}, "
          f"{args.stream_protocol} stream): {count} events ingested")


def cmd_realtime_quickstart(args) -> None:
    from pinot_tpu.tools.quickstart import run_realtime_quickstart

    cluster = run_realtime_quickstart(num_events=args.events, http=not args.no_http)
    if not args.no_http:
        print("Ctrl-C to exit.")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            cluster.stop()


def cmd_hybrid_quickstart(args) -> None:
    from pinot_tpu.tools.quickstart import run_hybrid_quickstart

    cluster = run_hybrid_quickstart(
        num_offline=args.offline_rows,
        num_realtime=args.realtime_rows,
        http=not args.no_http,
    )
    if not args.no_http:
        print("Ctrl-C to exit.")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            cluster.stop()


def cmd_start_cluster(args) -> None:
    from pinot_tpu.broker.broker import BrokerHttpServer
    from pinot_tpu.controller.controller import ControllerHttpServer
    from pinot_tpu.tools.cluster_harness import InProcessCluster

    cluster = InProcessCluster(num_servers=args.servers, data_dir=args.data_dir)
    broker_http = BrokerHttpServer(cluster.broker, port=args.broker_port)
    broker_http.start()
    cluster.broker_starter.url = f"http://127.0.0.1:{broker_http.port}"
    controller_http = ControllerHttpServer(cluster.controller, port=args.controller_port)
    controller_http.start()
    # register broker url for client discovery
    inst = cluster.controller.resources.instances.get("broker0")
    if inst is not None:
        inst.url = f"http://127.0.0.1:{broker_http.port}"
    print(f"controller: http://127.0.0.1:{controller_http.port}")
    print(f"broker:     http://127.0.0.1:{broker_http.port}/query")
    print("Ctrl-C to exit.")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        broker_http.stop()
        controller_http.stop()
        cluster.stop()


def _serve_forever(stoppers) -> None:
    print("Ctrl-C to exit.", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        for s in stoppers:
            s()


def cmd_start_controller(args) -> None:
    """Standalone controller process (ControllerStarter.java:47 analog)."""
    from pinot_tpu.controller.controller import Controller, ControllerHttpServer

    ctrl = Controller(args.data_dir, start_managers=True)
    ctrl.gateway.heartbeat_timeout_s = args.heartbeat_timeout
    http = ControllerHttpServer(ctrl, port=args.port)
    http.start()
    print(f"READY controller http://127.0.0.1:{http.port}", flush=True)
    _serve_forever([http.stop, ctrl.stop])


def cmd_start_server(args) -> None:
    """Standalone server process joining a remote controller
    (HelixServerStarter.java:63 analog)."""
    from pinot_tpu.server.network_starter import NetworkedServerStarter

    starter = NetworkedServerStarter(
        args.controller, args.name, port=args.port, data_dir=args.data_dir
    )
    starter.start()
    print(f"READY server {starter.tcp.address[0]}:{starter.tcp.address[1]}", flush=True)
    _serve_forever([starter.stop])


def cmd_start_broker(args) -> None:
    """Standalone broker process joining a remote controller
    (HelixBrokerStarter.java:57 analog)."""
    from pinot_tpu.broker.network_starter import NetworkedBrokerStarter

    starter = NetworkedBrokerStarter(args.controller, args.name, port=args.port)
    starter.start()
    print(f"READY broker http://127.0.0.1:{starter.http.port}", flush=True)
    _serve_forever([starter.stop])


def cmd_start_stream_broker(args) -> None:
    """Standalone TCP stream-broker process (the Kafka-broker role for
    realtime ingestion; realtime/netstream.py)."""
    from pinot_tpu.realtime.netstream import StreamBrokerServer

    broker = StreamBrokerServer(port=args.port, log_dir=args.log_dir)
    broker.start()
    print(f"READY streambroker {broker.address[0]}:{broker.address[1]}", flush=True)
    _serve_forever([broker.stop])


def cmd_create_segment(args) -> None:
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.segment.columnar import build_segment_from_csv
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.segment.readers import read_jsonl
    from pinot_tpu.startree.builder import StarTreeBuilderConfig

    with open(args.schema_file) as f:
        schema = Schema.from_json(json.load(f))
    cfg = StarTreeBuilderConfig() if args.startree else None
    if args.data_file.endswith(".csv"):
        # columnar path (native one-pass parse when available)
        seg = build_segment_from_csv(
            schema, args.data_file, args.table, args.segment_name, startree_config=cfg
        )
    else:
        from pinot_tpu.segment.readers import read_for_path

        rows = read_for_path(args.data_file, schema)  # avro / jsonl
        seg = build_segment(
            schema, rows, args.table, args.segment_name, startree_config=cfg
        )
    path = write_segment(seg, args.out_dir)
    print(f"built segment {seg.segment_name}: {seg.num_docs} docs -> {path}")


def cmd_batch_create_segments(args) -> None:
    """pinot-hadoop analog: one segment build per input file on a
    worker-process pool, optional push (SegmentCreationJob.java)."""
    import glob as _glob

    from pinot_tpu.tools.batch_build import BatchBuildSpec, run_batch_build

    inputs = sorted(
        f
        for pat in args.inputs
        for f in _glob.glob(pat)
        if os.path.isfile(f)
    )
    if not inputs:
        raise SystemExit(f"no input files matched {args.inputs}")
    spec = BatchBuildSpec(
        schema_file=args.schema_file,
        table=args.table,
        input_files=inputs,
        out_dir=args.out_dir,
        controller=args.controller,
        startree=args.startree,
        segment_name_prefix=args.segment_name_prefix,
    )
    if args.remote_workers:
        from pinot_tpu.tools.batch_build import run_distributed_build

        addrs = []
        for part in args.remote_workers.split(","):
            part = part.strip()
            if not part:
                continue  # tolerate trailing commas
            host, sep, port = part.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise SystemExit(
                    f"-remote-workers: {part!r} is not host:port "
                    "(expected e.g. 10.0.0.5:9600,10.0.0.6:9600)"
                )
            addrs.append((host, int(port)))
        if not addrs:
            raise SystemExit("-remote-workers: no worker addresses given")
        results = run_distributed_build(spec, addrs)
    else:
        results = run_batch_build(spec, workers=args.workers)
    for r in results:
        print(json.dumps(r))


def cmd_start_build_worker(args) -> None:
    """Serve segment-build jobs over TCP (SegmentCreationJob mapper
    analog) until interrupted."""
    import time as _time

    from pinot_tpu.tools.batch_build import serve_build_worker

    server = serve_build_worker(host=args.host, port=args.port)
    print(f"build worker listening on {server.host}:{server.port}")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        pass


def cmd_upload_segment(args) -> None:
    with open(args.segment_file, "rb") as f:
        data = f.read()
    url = args.controller.rstrip("/") + f"/segments/{args.table}"
    req = urllib.request.Request(url, data=data, headers={"Content-Type": "application/octet-stream"})
    with urllib.request.urlopen(req, timeout=120) as r:
        print(json.loads(r.read()))


def cmd_add_schema(args) -> None:
    with open(args.schema_file) as f:
        payload = json.load(f)
    print(_post(args.controller.rstrip("/") + "/schemas", payload))


def cmd_add_table(args) -> None:
    with open(args.config_file) as f:
        payload = json.load(f)
    print(_post(args.controller.rstrip("/") + "/tables", payload))


def cmd_post_query(args) -> None:
    out = _post(args.broker.rstrip("/") + "/query", {"pql": args.query, "trace": args.trace})
    print(json.dumps(out, indent=2))


def cmd_query_runner(args) -> None:
    from pinot_tpu.tools.query_runner import QueryRunner, http_query_fn

    with open(args.query_file) as f:
        queries = [q.strip() for q in f if q.strip()]
    runner = QueryRunner(http_query_fn(args.broker))
    if args.mode == "singleThread":
        report = runner.single_thread(queries, rounds=args.rounds)
    elif args.mode == "multiThreads":
        report = runner.multi_threads(queries, num_threads=args.threads, rounds=args.rounds)
    else:
        report = runner.target_qps(queries, qps=args.qps, duration_s=args.duration)
    print(json.dumps(report.to_json(), indent=2))


def cmd_rebalance_table(args) -> None:
    url = args.controller.rstrip("/") + f"/tables/{args.table}/rebalance"
    if args.dry_run:
        url += "?dryRun=true"
    print(json.dumps(_post(url, {}), indent=2))


def cmd_add_tenant(args) -> None:
    print(
        _post(
            args.controller.rstrip("/") + "/tenants",
            {"name": args.name, "role": args.role, "count": args.count},
        )
    )


def cmd_list_tenants(args) -> None:
    with urllib.request.urlopen(args.controller.rstrip("/") + "/tenants", timeout=30) as r:
        print(json.dumps(json.loads(r.read()), indent=2))


def cmd_show_segment(args) -> None:
    from pinot_tpu.segment.format import read_segment

    seg = read_segment(args.segment_dir)
    print(json.dumps(seg.metadata.to_json(), indent=2, default=str))


def cmd_convert_segment(args) -> None:
    from pinot_tpu.tools.converters import segment_to_csv, segment_to_jsonl

    if args.format == "csv":
        n = segment_to_csv(args.segment_dir, args.out_file)
    else:
        n = segment_to_jsonl(args.segment_dir, args.out_file)
    print(f"exported {n} rows -> {args.out_file}")


def cmd_show_star_tree(args) -> None:
    from pinot_tpu.tools.converters import star_tree_summary

    print(json.dumps(star_tree_summary(args.segment_dir, max_nodes=args.max_nodes), indent=2))


def cmd_generate_data(args) -> None:
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.tools.datagen import random_rows

    with open(args.schema_file) as f:
        schema = Schema.from_json(json.load(f))
    rows = random_rows(schema, args.num_rows, seed=args.seed)
    with open(args.out_file, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"generated {len(rows)} rows -> {args.out_file}")


def main(argv=None) -> None:
    import logging
    import os

    lvl = os.environ.get("PINOT_TPU_LOGLEVEL", "WARNING").upper()
    if not isinstance(getattr(logging, lvl, None), int):
        lvl = "WARNING"  # unknown names must not kill a role process
    logging.basicConfig(
        level=lvl,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    n = os.environ.get("PINOT_TPU_FORCE_CPU")
    if n:
        # test harnesses run role processes on a virtual CPU mesh (the
        # sitecustomize otherwise dials the single-chip TPU tunnel)
        from pinot_tpu.utils.platform import force_cpu_mesh

        force_cpu_mesh(int(n))

    p = argparse.ArgumentParser(prog="pinot_tpu-admin", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    q = sub.add_parser("Quickstart")
    q.add_argument("-rows", type=int, default=10_000)
    q.add_argument("-startree", action="store_true")
    q.add_argument("-no-http", action="store_true")
    q.set_defaults(fn=cmd_quickstart)

    rq = sub.add_parser("RealtimeQuickstart")
    rq.add_argument("-events", type=int, default=2000)
    rq.add_argument("-no-http", action="store_true")
    rq.set_defaults(fn=cmd_realtime_quickstart)

    hq = sub.add_parser("HybridQuickstart")
    hq.add_argument("-offline-rows", type=int, default=1500, dest="offline_rows")
    hq.add_argument("-realtime-rows", type=int, default=800, dest="realtime_rows")
    hq.add_argument("-no-http", action="store_true")
    hq.set_defaults(fn=cmd_hybrid_quickstart)

    nrq = sub.add_parser("NetworkRealtimeQuickstart")
    nrq.add_argument("-events", type=int, default=2000)
    nrq.add_argument("-consumer-type", default="lowlevel",
                     choices=["lowlevel", "highlevel"], dest="consumer_type")
    nrq.add_argument("-stream-protocol", default="native",
                     choices=["native", "kafka"], dest="stream_protocol")
    nrq.set_defaults(fn=cmd_network_realtime_quickstart)

    sc = sub.add_parser("StartCluster")
    sc.add_argument("-servers", type=int, default=2)
    sc.add_argument("-data-dir", default=None)
    sc.add_argument("-broker-port", type=int, default=8099)
    sc.add_argument("-controller-port", type=int, default=9000)
    sc.set_defaults(fn=cmd_start_cluster)

    stc = sub.add_parser("StartController")
    stc.add_argument("-port", type=int, default=9000)
    stc.add_argument("-data-dir", required=True, dest="data_dir")
    stc.add_argument("-heartbeat-timeout", type=float, default=6.0, dest="heartbeat_timeout")
    stc.set_defaults(fn=cmd_start_controller)

    sts = sub.add_parser("StartServer")
    sts.add_argument("-controller", default="http://127.0.0.1:9000")
    sts.add_argument("-name", default="server0")
    sts.add_argument("-port", type=int, default=0)
    sts.add_argument("-data-dir", default=None, dest="data_dir")
    sts.set_defaults(fn=cmd_start_server)

    stb = sub.add_parser("StartBroker")
    stb.add_argument("-controller", default="http://127.0.0.1:9000")
    stb.add_argument("-name", default="broker0")
    stb.add_argument("-port", type=int, default=8099)
    stb.set_defaults(fn=cmd_start_broker)

    ssb = sub.add_parser("StartStreamBroker")
    ssb.add_argument("-port", type=int, default=0)
    ssb.add_argument("-log-dir", default=None, dest="log_dir")
    ssb.set_defaults(fn=cmd_start_stream_broker)

    cs = sub.add_parser("CreateSegment")
    cs.add_argument("-schema-file", required=True, dest="schema_file")
    cs.add_argument("-data-file", required=True, dest="data_file")
    cs.add_argument("-table", required=True)
    cs.add_argument("-segment-name", required=True, dest="segment_name")
    cs.add_argument("-out-dir", required=True, dest="out_dir")
    cs.add_argument("-startree", action="store_true")
    cs.set_defaults(fn=cmd_create_segment)

    bcs = sub.add_parser("BatchCreateSegments")
    bcs.add_argument("-schema-file", required=True, dest="schema_file")
    bcs.add_argument("-inputs", required=True, nargs="+", help="input files/globs (csv/jsonl/avro), one segment each")
    bcs.add_argument("-table", required=True)
    bcs.add_argument("-out-dir", required=True, dest="out_dir")
    bcs.add_argument("-controller", default=None, help="push built segments here when set")
    bcs.add_argument("-workers", type=int, default=0)
    bcs.add_argument(
        "-remote-workers",
        default=None,
        dest="remote_workers",
        help="comma-separated host:port build workers (StartBuildWorker); "
        "fans shards out over TCP instead of the local process pool",
    )
    bcs.add_argument("-startree", action="store_true")
    bcs.add_argument("-segment-name-prefix", default=None, dest="segment_name_prefix")
    bcs.set_defaults(fn=cmd_batch_create_segments)

    sbw = sub.add_parser(
        "StartBuildWorker",
        help="long-lived remote segment-build worker (Hadoop-mapper analog)",
    )
    sbw.add_argument("-host", default="0.0.0.0")
    sbw.add_argument("-port", type=int, default=9600)
    sbw.set_defaults(fn=cmd_start_build_worker)

    us = sub.add_parser("UploadSegment")
    us.add_argument("-controller", default="http://127.0.0.1:9000")
    us.add_argument("-table", required=True)
    us.add_argument("-segment-file", required=True, dest="segment_file")
    us.set_defaults(fn=cmd_upload_segment)

    asch = sub.add_parser("AddSchema")
    asch.add_argument("-controller", default="http://127.0.0.1:9000")
    asch.add_argument("-schema-file", required=True, dest="schema_file")
    asch.set_defaults(fn=cmd_add_schema)

    at = sub.add_parser("AddTable")
    at.add_argument("-controller", default="http://127.0.0.1:9000")
    at.add_argument("-config-file", required=True, dest="config_file")
    at.set_defaults(fn=cmd_add_table)

    pq = sub.add_parser("PostQuery")
    pq.add_argument("-broker", default="http://127.0.0.1:8099")
    pq.add_argument("-query", required=True)
    pq.add_argument("-trace", action="store_true")
    pq.set_defaults(fn=cmd_post_query)

    qr = sub.add_parser("QueryRunner")
    qr.add_argument("-broker", default="http://127.0.0.1:8099")
    qr.add_argument("-query-file", required=True, dest="query_file")
    qr.add_argument("-mode", choices=["singleThread", "multiThreads", "targetQPS"], default="singleThread")
    qr.add_argument("-rounds", type=int, default=1)
    qr.add_argument("-threads", type=int, default=4)
    qr.add_argument("-qps", type=float, default=10.0)
    qr.add_argument("-duration", type=float, default=10.0)
    qr.set_defaults(fn=cmd_query_runner)

    rb = sub.add_parser("RebalanceTable")
    rb.add_argument("-controller", default="http://127.0.0.1:9000")
    rb.add_argument("-table", required=True)
    rb.add_argument("-dry-run", action="store_true", dest="dry_run")
    rb.set_defaults(fn=cmd_rebalance_table)

    ate = sub.add_parser("AddTenant")
    ate.add_argument("-controller", default="http://127.0.0.1:9000")
    ate.add_argument("-name", required=True)
    ate.add_argument("-role", choices=["server", "broker"], default="server")
    ate.add_argument("-count", type=int, default=1)
    ate.set_defaults(fn=cmd_add_tenant)

    lt = sub.add_parser("ListTenants")
    lt.add_argument("-controller", default="http://127.0.0.1:9000")
    lt.set_defaults(fn=cmd_list_tenants)

    ss = sub.add_parser("ShowSegment")
    ss.add_argument("-segment-dir", required=True, dest="segment_dir")
    ss.set_defaults(fn=cmd_show_segment)

    cv = sub.add_parser("ConvertSegment")
    cv.add_argument("-segment-dir", required=True, dest="segment_dir")
    cv.add_argument("-format", choices=["csv", "jsonl"], default="jsonl")
    cv.add_argument("-out-file", required=True, dest="out_file")
    cv.set_defaults(fn=cmd_convert_segment)

    sst = sub.add_parser("ShowStarTree")
    sst.add_argument("-segment-dir", required=True, dest="segment_dir")
    sst.add_argument("-max-nodes", type=int, default=50, dest="max_nodes")
    sst.set_defaults(fn=cmd_show_star_tree)

    gd = sub.add_parser("GenerateData")
    gd.add_argument("-schema-file", required=True, dest="schema_file")
    gd.add_argument("-num-rows", type=int, default=1000, dest="num_rows")
    gd.add_argument("-seed", type=int, default=0)
    gd.add_argument("-out-file", required=True, dest="out_file")
    gd.set_defaults(fn=cmd_generate_data)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
