"""Cluster doctor: one postmortem bundle from every role's debug surface.

When something went wrong — an SLO burn, a shed burst, a dead server —
the evidence is scattered across the controller's rollups, each
broker's history/SLO/tail rings, each server's device and plan
registries, and whatever flight-recorder bundles the roles dumped on
disk.  The doctor walks all of it from ONE entry point (the controller
URL), concurrently fetches every role's debug endpoints, inlines any
locally-readable flight-recorder bundles, and writes a single JSON
document an operator (or a later tool) can take away:

    {
      "ts": ..., "controllerUrl": ...,
      "controller": {"<endpoint>": <payload> | {"error": ...}, ...},
      "instances": {name: {"role": ..., "url": ...,
                           "endpoints": {...}, "flightBundles": [...]}},
      "summary": {...}           # the at-a-glance postmortem header
    }

Instance discovery rides ``/debug/clustermetrics`` (role + url per
registered instance), so the doctor needs no out-of-band inventory.
Every fetch degrades independently to an ``{"error": ...}`` entry — a
half-dead cluster yields a half-full bundle, never an exception.

Usage:
  python -m pinot_tpu.tools.doctor http://127.0.0.1:9000 \\
      [--out bundle.json] [--timeout 5] [--history-window 900]

Exit codes: 0 bundle written (possibly partial), 2 controller
unreachable (nothing to collect).
"""
from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

# per-role debug endpoints the doctor pulls.  History fetches append
# ?windowS= so a long-lived ring doesn't bloat the bundle.
CONTROLLER_ENDPOINTS = [
    "/health",
    "/debug/metrics",
    "/debug/slo",
    "/debug/history",
    "/debug/flightrec",
    "/debug/stabilizer",
    "/debug/capacity",
    "/debug/workload",
    "/debug/utilization",
    "/debug/audit",
    "/clusterstate",
]
BROKER_ENDPOINTS = [
    "/debug/metrics",
    "/debug/queries",
    "/debug/slo",
    "/debug/tails?traces=true",
    "/debug/history",
    "/debug/admission",
    "/debug/workload",
    "/debug/audit",
    "/debug/flightrec",
]
SERVER_ENDPOINTS = [
    "/debug/metrics",
    "/debug/device",
    "/debug/plans",
    "/debug/history",
    "/debug/profile",
    "/debug/audit",
    "/debug/flightrec",
]

ENDPOINTS_BY_ROLE = {"broker": BROKER_ENDPOINTS, "server": SERVER_ENDPOINTS}


def _fetch_json(url: str, timeout_s: float) -> Any:
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read())
    except (urllib.error.URLError, OSError, ValueError) as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _fetch_endpoints(
    base: str, endpoints: List[str], timeout_s: float, history_window_s: float
) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for ep in endpoints:
        url = base.rstrip("/") + ep
        if ep.endswith("/debug/history"):
            url += f"?windowS={history_window_s:g}"
        out[ep] = _fetch_json(url, timeout_s)
    return out


def _inline_flight_bundles(flightrec: Any, limit: int = 16) -> List[Dict[str, Any]]:
    """When the role's flight-recorder directory is readable from THIS
    process (in-process harness, same-host postmortem), inline the
    bundle documents themselves; otherwise the inventory from
    ``/debug/flightrec`` is all the doctor can carry."""
    if not isinstance(flightrec, dict):
        return []
    d = flightrec.get("dir")
    if not d or not os.path.isdir(d):
        return []
    out: List[Dict[str, Any]] = []
    for entry in (flightrec.get("bundles") or [])[-limit:]:
        path = os.path.join(d, entry.get("file", ""))
        try:
            with open(path, "r", encoding="utf-8") as f:
                out.append(json.load(f))
        except (OSError, ValueError) as e:
            out.append({"file": entry.get("file"), "error": str(e)})
    return out


def collect(
    controller_url: str,
    timeout_s: float = 5.0,
    history_window_s: float = 900.0,
) -> Dict[str, Any]:
    """The whole postmortem bundle as one dict (pure HTTP + local
    flight-bundle reads; unit-testable against an in-process cluster)."""
    base = controller_url.rstrip("/")
    bundle: Dict[str, Any] = {
        "ts": round(time.time(), 3),
        "controllerUrl": base,
        "controller": _fetch_endpoints(
            base, CONTROLLER_ENDPOINTS, timeout_s, history_window_s
        ),
        "instances": {},
    }
    bundle["controller"]["flightBundles"] = _inline_flight_bundles(
        bundle["controller"].get("/debug/flightrec")
    )

    cm = _fetch_json(base + "/debug/clustermetrics", timeout_s)
    instances = cm.get("instances") if isinstance(cm, dict) else None

    def visit(item):
        name, meta = item
        role = meta.get("role")
        url = meta.get("url")
        entry: Dict[str, Any] = {"role": role, "url": url}
        eps = ENDPOINTS_BY_ROLE.get(role)
        if not url:
            entry["error"] = "no HTTP surface registered"
        elif eps is None:
            entry["error"] = f"unknown role {role!r}"
        else:
            entry["endpoints"] = _fetch_endpoints(
                url, eps, timeout_s, history_window_s
            )
            entry["flightBundles"] = _inline_flight_bundles(
                entry["endpoints"].get("/debug/flightrec")
            )
        return name, entry

    items = sorted((instances or {}).items())
    if items:
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, len(items))
        ) as pool:
            bundle["instances"] = dict(pool.map(visit, items))
    bundle["summary"] = summarize(bundle)
    return bundle


def summarize(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """At-a-glance postmortem header computed from the collected
    payloads — what an operator reads before opening anything else."""
    ctrl = bundle.get("controller") or {}
    slo = ctrl.get("/debug/slo") or {}
    instances = bundle.get("instances") or {}
    roles: Dict[str, int] = {}
    errors = 0
    retained_tails = 0
    flight_bundles = len(ctrl.get("flightBundles") or [])
    # correctness & freshness audit rollup (ISSUE 19): total divergence
    # evidence across every plane, plus the stalest realtime tables —
    # the postmortem lines an operator reads before anything else
    shadow_divergences = 0
    replica_divergences = 0
    quarantined: List[Dict[str, Any]] = []
    worst_freshness: List[Dict[str, Any]] = []
    audit_bundle_count = 0
    ctrl_audit = ctrl.get("/debug/audit") or {}
    crc_mismatches = (
        len(ctrl_audit.get("mismatches") or [])
        if isinstance(ctrl_audit, dict)
        else 0
    )

    def _count_audit_bundles(bundles) -> int:
        return sum(
            1
            for b in bundles or []
            if isinstance(b, dict)
            and str(b.get("reason", "")).lower().endswith("divergence")
        )

    audit_bundle_count += _count_audit_bundles(ctrl.get("flightBundles"))
    for entry in instances.values():
        roles[entry.get("role") or "?"] = roles.get(entry.get("role") or "?", 0) + 1
        if "error" in entry:
            errors += 1
            continue
        flight_bundles += len(entry.get("flightBundles") or [])
        audit_bundle_count += _count_audit_bundles(entry.get("flightBundles"))
        for ep, payload in (entry.get("endpoints") or {}).items():
            if isinstance(payload, dict) and "error" in payload and len(payload) == 1:
                errors += 1
            if ep.startswith("/debug/tails") and isinstance(payload, dict):
                retained_tails += int(payload.get("retained") or 0)
            if ep == "/debug/audit" and isinstance(payload, dict):
                if entry.get("role") == "server":
                    shadow_divergences += int(payload.get("divergences") or 0)
                    quarantined.extend(payload.get("quarantined") or [])
                elif entry.get("role") == "broker":
                    replica = payload.get("replica") or {}
                    replica_divergences += int(replica.get("divergences") or 0)
                    fresh = payload.get("freshness")
                    if isinstance(fresh, dict) and fresh.get("tables"):
                        from pinot_tpu.broker.freshness import (
                            worst_freshness_tables,
                        )

                        worst_freshness = worst_freshness_tables(fresh)
    return {
        "instances": roles,
        "fetchErrors": errors,
        "burningTables": slo.get("burningTables") or [],
        "worstBurning": slo.get("worstBurning") or [],
        "retainedTails": retained_tails,
        "flightBundles": flight_bundles,
        "audit": {
            "shadowDivergences": shadow_divergences,
            "replicaDivergences": replica_divergences,
            "crcMismatches": crc_mismatches,
            "quarantined": quarantined,
            "divergenceBundles": audit_bundle_count,
            "worstFreshnessTables": worst_freshness,
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="pinot_tpu-doctor", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("controller", help="controller base URL (http://host:port)")
    p.add_argument(
        "--out",
        default=None,
        help="bundle file path (default doctor-<millis>.json in cwd; "
        "- for stdout)",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument(
        "--history-window",
        type=float,
        default=900.0,
        help="seconds of metric history to pull per role",
    )
    args = p.parse_args(argv)

    probe = _fetch_json(args.controller.rstrip("/") + "/health", args.timeout)
    if isinstance(probe, dict) and set(probe) == {"error"}:
        print(
            json.dumps({"error": f"controller unreachable: {probe['error']}"}),
            file=sys.stderr,
        )
        return 2

    bundle = collect(
        args.controller,
        timeout_s=args.timeout,
        history_window_s=args.history_window,
    )
    text = json.dumps(bundle, indent=1)
    if args.out == "-":
        print(text)
    else:
        out = args.out or f"doctor-{int(bundle['ts'] * 1000)}.json"
        with open(out, "w", encoding="utf-8") as f:
            f.write(text)
        print(out)
    print(json.dumps(bundle["summary"]), file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
