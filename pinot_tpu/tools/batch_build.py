"""Distributed/batch segment build: the pinot-hadoop analog.

Reference: ``pinot-hadoop/.../job/SegmentCreationJob.java`` maps one
segment build per input file across a Hadoop cluster, then
``SegmentTarPushJob`` POSTs the tars to the controller.  Here the same
shape runs on a worker-process pool: shard input files -> build a
segment per shard in a subprocess (CSV fast path uses the native C++
parser) -> write to the output dir -> optionally push to a controller
over HTTP.  Build work is host-side numpy, so worker processes scale it
across cores without touching the TPU.
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import urllib.request
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass
class BatchBuildSpec:
    schema_file: str
    table: str
    input_files: Sequence[str]
    out_dir: str
    controller: Optional[str] = None  # push after build when set
    startree: bool = False
    segment_name_prefix: Optional[str] = None  # default: table name


def _build_one(args: Tuple[str, str, str, str, str, bool, Optional[str]]) -> dict:
    """Worker: build one segment from one input file (runs in a spawned
    subprocess, like one Hadoop mapper)."""
    schema_file, table, input_file, out_dir, segment_name, startree, controller = args
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.segment.columnar import build_segment_from_csv
    from pinot_tpu.segment.format import write_segment
    from pinot_tpu.startree.builder import StarTreeBuilderConfig

    with open(schema_file) as f:
        schema = Schema.from_json(json.load(f))
    cfg = StarTreeBuilderConfig() if startree else None
    if input_file.endswith(".csv"):
        seg = build_segment_from_csv(
            schema, input_file, table, segment_name, startree_config=cfg
        )
    else:
        from pinot_tpu.segment.readers import read_for_path

        rows = read_for_path(input_file, schema)
        seg = build_segment(schema, rows, table, segment_name, startree_config=cfg)
    path = write_segment(seg, os.path.join(out_dir, segment_name))
    result = {
        "segment": segment_name,
        "input": input_file,
        "docs": seg.num_docs,
        "path": path,
        "pushed": False,
    }
    if controller:
        with open(path, "rb") as f:
            data = f.read()
        url = controller.rstrip("/") + f"/segments/{table}"
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/octet-stream"}
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            json.loads(r.read())
        result["pushed"] = True
    return result


def run_batch_build(spec: BatchBuildSpec, workers: int = 0) -> List[dict]:
    """Build (and optionally push) one segment per input file on a
    process pool; returns per-segment results in input order."""
    if not spec.input_files:
        return []
    os.makedirs(spec.out_dir, exist_ok=True)
    prefix = spec.segment_name_prefix or spec.table
    jobs = [
        (
            spec.schema_file,
            spec.table,
            path,
            spec.out_dir,
            f"{prefix}_{i}",
            spec.startree,
            spec.controller,
        )
        for i, path in enumerate(spec.input_files)
    ]
    workers = workers or min(len(jobs), os.cpu_count() or 2)
    if workers <= 1 or len(jobs) == 1:
        return [_build_one(j) for j in jobs]
    # spawn (not fork): workers must not inherit initialized jax/TPU
    # state from the parent
    ctx = mp.get_context("spawn")
    with ctx.Pool(workers) as pool:
        return pool.map(_build_one, jobs)


# ---------------------------------------------------------------------------
# Cross-machine build fan-out (VERDICT r3 #2 / pinot-hadoop parity)
# ---------------------------------------------------------------------------
# Reference: SegmentCreationJob.java distributes one segment build per
# input file across Hadoop mappers; SegmentTarPushJob.java pushes the
# results.  Here remote BUILD WORKERS are long-lived OS processes
# serving length-framed JSON jobs over the framework's own TCP
# transport (transport/tcp.py); the coordinator shards inputs across
# workers and retries failed shards on surviving workers.  Workers
# push finished segments to the controller themselves (the mapper-side
# push), so segment bytes never funnel through the coordinator.


def _worker_handle(payload: bytes) -> bytes:
    """One build job frame -> one result frame (runs inside a worker)."""
    job = json.loads(payload.decode("utf-8"))
    try:
        result = _build_one(
            (
                job["schemaFile"],
                job["table"],
                job["inputFile"],
                job["outDir"],
                job["segmentName"],
                bool(job.get("startree")),
                job.get("controller"),
            )
        )
        return json.dumps({"ok": True, "result": result}).encode("utf-8")
    except Exception as e:  # report, don't kill the worker
        return json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"}).encode(
            "utf-8"
        )


def serve_build_worker(host: str = "127.0.0.1", port: int = 0):
    """Start a build worker; returns the TcpServer (its .address is the
    (host, port) the coordinator needs)."""
    from pinot_tpu.transport.tcp import TcpServer

    server = TcpServer(_worker_handle, host=host, port=port)
    server.start()
    return server


def run_distributed_build(
    spec: BatchBuildSpec,
    worker_addresses: Sequence[Tuple[str, int]],
    retries: int = 2,
    timeout_s: float = 600.0,
) -> List[dict]:
    """Fan one build job per input file out to remote build workers.

    Shards are dealt round-robin; a shard whose worker fails (connection
    refused, worker crash mid-build, error reply) is retried on the
    next worker, up to ``retries`` extra attempts — the Hadoop-mapper
    re-execution analog.  Raises RuntimeError when a shard exhausts its
    attempts; per-shard results come back in input order."""
    from concurrent.futures import ThreadPoolExecutor

    from pinot_tpu.transport.tcp import TcpTransport, TransportError

    if not spec.input_files:
        return []
    os.makedirs(spec.out_dir, exist_ok=True)
    prefix = spec.segment_name_prefix or spec.table
    with open(spec.schema_file):  # fail fast on a bad schema path
        pass
    transport = TcpTransport()
    n_workers = len(worker_addresses)

    def run_shard(i_path):
        i, path = i_path
        job = json.dumps(
            {
                "schemaFile": spec.schema_file,
                "table": spec.table,
                "inputFile": path,
                "outDir": spec.out_dir,
                "segmentName": f"{prefix}_{i}",
                "startree": spec.startree,
                "controller": spec.controller,
            }
        ).encode("utf-8")
        errors = []
        for attempt in range(retries + 1):
            addr = tuple(worker_addresses[(i + attempt) % n_workers])
            try:
                reply = json.loads(
                    transport.request(addr, job, timeout=timeout_s).decode("utf-8")
                )
            except (TransportError, OSError) as e:
                # OSError covers pool checkout (fresh connect) to a dead
                # worker — connection refused must retry like any failure
                errors.append(f"{addr}: {e}")
                continue
            if reply.get("ok"):
                return reply["result"]
            errors.append(f"{addr}: {reply.get('error')}")
        raise RuntimeError(
            f"shard {i} ({path}) failed on all attempts: {'; '.join(errors)}"
        )

    with ThreadPoolExecutor(max_workers=min(len(spec.input_files), 16)) as pool:
        return list(pool.map(run_shard, enumerate(spec.input_files)))
