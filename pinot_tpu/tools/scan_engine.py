"""Scan-based reference query engine — the correctness oracle.

Pure-Python row-at-a-time evaluator of a BrokerRequest over in-memory
records.  Plays the role of the reference's golden model
(pinot-tools ``tools/scan/query/ScanBasedQueryProcessor.java:40``), used
by sentinel and differential tests to pin the TPU engine's semantics.

Semantics notes (matched to the reference engine):

- Predicate literals are compared in the column's stored type domain:
  numeric columns compare numerically, strings lexicographically.
- Multi-value (MV) columns: a row matches a positive predicate
  (EQ/IN/RANGE/REGEX) if ANY of its values matches; for negative
  predicates (NOT/NOT_IN) a row matches if NONE of its values is
  excluded (complement semantics).
- Group-by on an MV column produces one group per value in the row
  (rows are counted once per matching value).
- ``percentileNN`` is the exact reference formula: sort ascending, take
  ``sorted[int(n * NN/100)]`` (``quantile/PercentileUtil.java:50``).
  ``percentileestNN`` follows the same exact path here (the reference
  approximates with a q-digest; exactness is a superset of its contract).
- ``distinctcounthll`` / ``fasthll`` estimate cardinality with HLL; the
  oracle computes them through the same HLL sketch implementation used
  by the TPU engine (``pinot_tpu.engine.hll``) so results agree exactly.
- Group-by results are sorted by aggregated value, descending — except
  functions whose name starts with "min", which sort ascending
  (``AggregationGroupByOperatorService.java:146``) — and trimmed to TOP n.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from pinot_tpu.common.request import (
    AggregationInfo,
    BrokerRequest,
    FilterOperator,
    FilterQueryTree,
    RangeSpec,
    group_sort_ascending,
)
from pinot_tpu.common.response import (
    AggregationResult,
    BrokerResponse,
    GroupByResult,
    SelectionResults,
)
from pinot_tpu.common.schema import DataType, Schema

Row = Dict[str, Any]


# ---------------------------------------------------------------------------
# Predicate evaluation
# ---------------------------------------------------------------------------


def _coerce(literal: str, dt: DataType) -> Any:
    return dt.convert(literal)


def _values_of(row: Row, column: str) -> List[Any]:
    v = row[column]
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v]


class _LeafEvaluator:
    """Evaluates one leaf predicate against a row (PredicateEvaluator analog)."""

    def __init__(self, node: FilterQueryTree, schema: Schema) -> None:
        self.node = node
        self.column = node.column
        spec = schema.field(node.column)
        dt = spec.data_type
        self.is_string = dt.stored_type == DataType.STRING
        op = node.operator
        if op in (FilterOperator.EQUALITY, FilterOperator.IN):
            self.targets = {_coerce(v, dt) for v in node.values}
            self.negate = False
            self.kind = "set"
        elif op in (FilterOperator.NOT, FilterOperator.NOT_IN):
            self.targets = {_coerce(v, dt) for v in node.values}
            self.negate = True
            self.kind = "set"
        elif op == FilterOperator.RANGE:
            r = node.range_spec or RangeSpec()
            self.lower = _coerce(r.lower, dt) if r.lower is not None and r.lower != "*" else None
            self.upper = _coerce(r.upper, dt) if r.upper is not None and r.upper != "*" else None
            self.incl_lower = r.include_lower
            self.incl_upper = r.include_upper
            self.kind = "range"
        elif op == FilterOperator.REGEX:
            self.pattern = re.compile(node.values[0])
            self.kind = "regex"
        else:
            raise ValueError(f"unsupported leaf operator {op}")

    def _match_one(self, v: Any) -> bool:
        if self.kind == "set":
            return v in self.targets
        if self.kind == "range":
            if self.lower is not None:
                if self.incl_lower:
                    if v < self.lower:
                        return False
                elif v <= self.lower:
                    return False
            if self.upper is not None:
                if self.incl_upper:
                    if v > self.upper:
                        return False
                elif v >= self.upper:
                    return False
            return True
        if self.kind == "regex":
            return self.pattern.search(str(v)) is not None
        raise AssertionError

    def matches(self, row: Row) -> bool:
        vals = _values_of(row, self.column)
        if self.kind == "set" and self.negate:
            # NOT/NOT_IN over MV: no value may be in the excluded set.
            return all(v not in self.targets for v in vals)
        return any(self._match_one(v) for v in vals)


def _build_matcher(tree: Optional[FilterQueryTree], schema: Schema):
    if tree is None:
        return lambda row: True
    if tree.is_leaf:
        return _LeafEvaluator(tree, schema).matches
    child_fns = [_build_matcher(c, schema) for c in tree.children]
    if tree.operator == FilterOperator.AND:
        return lambda row: all(f(row) for f in child_fns)
    if tree.operator == FilterOperator.OR:
        return lambda row: any(f(row) for f in child_fns)
    raise ValueError(f"unsupported non-leaf operator {tree.operator}")


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------


def _numeric_values(row: Row, agg: AggregationInfo) -> List[float]:
    vals = _values_of(row, agg.column)
    return [float(v) for v in vals]


class _Accumulator:
    """One aggregation function's running state (oracle-side, exact)."""

    def __init__(self, agg: AggregationInfo) -> None:
        self.agg = agg
        base = agg.base_function
        self.base = base
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.distinct: set = set()
        self.values: List[float] = []  # for percentiles

    def add(self, row: Row) -> None:
        base = self.base
        if base == "count":
            if self.agg.is_mv:
                self.count += len(_values_of(row, self.agg.column))
            else:
                self.count += 1
            return
        if base in ("distinctcount", "distinctcounthll", "fasthll"):
            for v in _values_of(row, self.agg.column):
                self.distinct.add(v)
            return
        vals = _numeric_values(row, self.agg)
        for v in vals:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
        if base.startswith("percentile"):
            self.values.extend(vals)

    def result(self) -> Any:
        base = self.base
        if base == "count":
            return self.count
        if base == "sum":
            return self.sum
        if base == "min":
            return self.min
        if base == "max":
            return self.max
        if base == "avg":
            return self.sum / self.count if self.count else -math.inf
        if base == "minmaxrange":
            return self.max - self.min
        if base == "distinctcount":
            return len(self.distinct)
        if base in ("distinctcounthll", "fasthll"):
            from pinot_tpu.engine.hll import hll_estimate_exact_values

            return hll_estimate_exact_values(self.distinct)
        if base.startswith("percentileest"):
            p = int(base[len("percentileest"):])
            return _percentile(self.values, p)
        if base.startswith("percentile"):
            p = int(base[len("percentile"):])
            return _percentile(self.values, p)
        raise ValueError(f"unknown aggregation {base}")


def _percentile(values: List[float], p: int) -> float:
    """Reference formula: quantile/PercentileUtil.java:50."""
    if not values:
        return -math.inf
    s = sorted(values)
    idx = min(int(len(s) * p / 100.0), len(s) - 1)
    return s[idx]


def _group_sort_ascending(function: str) -> bool:
    """AggregationGroupByOperatorService.java:146 — min* sorts ascending."""
    return group_sort_ascending(function)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class ScanQueryProcessor:
    """Executes BrokerRequests over a list of rows by brute-force scan."""

    def __init__(self, schema: Schema, rows: Sequence[Row]) -> None:
        self.schema = schema
        # Normalize rows exactly like the segment builder: type-convert
        # every value, fill missing with default null values — so the
        # oracle sees the same stored values the engine does.
        self.rows = [self._normalize(r) for r in rows]

    def _normalize(self, row: Row) -> Row:
        out: Row = {}
        for spec in self.schema.all_fields():
            v = row.get(spec.name)
            if v is None:
                out[spec.name] = (
                    spec.get_default_null_value()
                    if spec.single_value
                    else [spec.get_default_null_value()]
                )
            elif spec.single_value:
                out[spec.name] = spec.stored_type.convert(v)
            else:
                vs = v if isinstance(v, (list, tuple)) else [v]
                out[spec.name] = [spec.stored_type.convert(x) for x in vs] or [
                    spec.get_default_null_value()
                ]
        return out

    def execute(self, request: BrokerRequest) -> BrokerResponse:
        matcher = _build_matcher(request.filter, self.schema)
        matched = [r for r in self.rows if matcher(r)]

        resp = BrokerResponse(
            num_docs_scanned=len(matched),
            total_docs=len(self.rows),
            num_segments_queried=1,
            num_servers_queried=1,
            num_servers_responded=1,
        )

        if request.is_aggregation:
            if request.is_group_by:
                resp.aggregation_results = self._group_by(request, matched)
            else:
                resp.aggregation_results = self._aggregate(request, matched)
        else:
            resp.selection_results = self._selection(request, matched)
        return resp

    # -- aggregation-only ---------------------------------------------
    def _aggregate(self, request: BrokerRequest, rows: List[Row]) -> List[AggregationResult]:
        out = []
        for agg in request.aggregations:
            acc = _Accumulator(agg)
            for row in rows:
                acc.add(row)
            out.append(AggregationResult(function=agg.display_name, value=acc.result()))
        return out

    # -- group-by ------------------------------------------------------
    def _group_keys(self, row: Row, columns: List[str]) -> List[Tuple[str, ...]]:
        """Cartesian product over MV group-by column values (Pinot MV
        group-by semantics: one group per MV value combination)."""
        keys: List[Tuple[str, ...]] = [()]
        for col in columns:
            vals = _values_of(row, col)
            keys = [k + (self._render(col, v),) for k in keys for v in vals]
        return keys

    def _render(self, column: str, v: Any) -> str:
        from pinot_tpu.common.values import render_value

        return render_value(self.schema.field(column).stored_type, v)

    def _group_by(self, request: BrokerRequest, rows: List[Row]) -> List[AggregationResult]:
        gb = request.group_by
        assert gb is not None
        groups: Dict[Tuple[str, ...], List[_Accumulator]] = {}
        for row in rows:
            for key in self._group_keys(row, gb.columns):
                accs = groups.get(key)
                if accs is None:
                    accs = [_Accumulator(a) for a in request.aggregations]
                    groups[key] = accs
                for acc in accs:
                    acc.add(row)

        out: List[AggregationResult] = []
        for i, agg in enumerate(request.aggregations):
            pairs = [(key, accs[i].result()) for key, accs in groups.items()]
            asc = _group_sort_ascending(agg.function)
            pairs.sort(key=lambda kv: (kv[1], kv[0]) if asc else (-kv[1], kv[0]))
            trimmed = pairs[: gb.top_n]
            out.append(
                AggregationResult(
                    function=agg.display_name,
                    group_by_columns=list(gb.columns),
                    group_by_result=[GroupByResult(group=list(k), value=v) for k, v in trimmed],
                )
            )
        return out

    # -- selection -----------------------------------------------------
    def _selection(self, request: BrokerRequest, rows: List[Row]) -> SelectionResults:
        sel = request.selection
        assert sel is not None
        columns = sel.columns
        if columns == ["*"] or not columns:
            columns = self.schema.column_names

        if sel.sorts:
            def sort_key(row: Row):
                key = []
                for s in sel.sorts:
                    v = row[s.column]
                    if isinstance(v, (list, tuple)):
                        v = v[0] if v else None
                    key.append(_Reversible(v, not s.ascending))
                return key

            ordered = sorted(rows, key=sort_key)
        else:
            ordered = rows

        window = ordered[sel.offset : sel.offset + sel.size]
        out_rows = [[row[c] for c in columns] for row in window]
        return SelectionResults(columns=list(columns), rows=out_rows)


class _Reversible:
    """Sort-key wrapper supporting per-column descending order."""

    __slots__ = ("v", "desc")

    def __init__(self, v: Any, desc: bool) -> None:
        self.v = v
        self.desc = desc

    def __lt__(self, other: "_Reversible") -> bool:
        if self.desc:
            return other.v < self.v
        return self.v < other.v

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _Reversible) and self.v == other.v
