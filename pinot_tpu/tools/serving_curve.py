"""Serving curve under concurrent load: QPS vs latency + overload point.

The reference measures serving capacity by replaying queries at a target
QPS and recording latency percentiles (``QueryRunner.java:45-53``,
PinotResponseTime methodology).  This tool drives the full in-process
broker path (parse -> route -> scatter -> kernel -> reduce) with a MIXED
workload at a rising QPS ladder and records, per step:

  target QPS, achieved QPS, p50/p90/p99 ms, error count, shed count
  (scheduler saturation replies, error code 210), scheduler shed total

The saturation point is the first step where achieved QPS falls below
90% of target or queries start shedding.  Output: one JSON document
(stdout, and -out file) suitable for committing as the round's serving
curve artifact.

Usage:
  python -m pinot_tpu.tools.serving_curve                       # on-chip shape
  python -m pinot_tpu.tools.serving_curve -segments 2 \
      -rows-per-segment 250000 -qps 2,4,8 -duration 5           # CPU smoke
"""
from __future__ import annotations

import argparse
import json
import threading
import time
from typing import List

from pinot_tpu.common.response import ErrorCode


def mixed_workload(segments) -> List[str]:
    """The four BASELINE.md query shapes: flagship group-by scan (Q1),
    IN+range group-by (Q6-like), selective needle, HLL distinct."""
    d_price = segments[0].column("l_extendedprice").dictionary
    pv = d_price.get(d_price.cardinality // 2)
    return [
        "SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
        "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus TOP 10",
        "SELECT sum(l_extendedprice) FROM lineitem "
        "WHERE l_shipmode IN ('RAIL','FOB') AND "
        "l_receiptdate BETWEEN '1997-01-01' AND '1997-12-31' "
        "GROUP BY l_shipmode TOP 10",
        f"SELECT sum(l_quantity), count(*) FROM lineitem "
        f"WHERE l_extendedprice = {pv!r}",
        "SELECT distinctcounthll(l_shipdate) FROM lineitem "
        "GROUP BY l_returnflag TOP 10",
    ]


def run_curve(
    segments,
    qps_ladder: List[float],
    duration_s: float,
    max_pending: int = 24,
) -> dict:
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.query_runner import QueryRunner

    # max_pending BELOW the runner's 32-thread concurrency cap, so the
    # scheduler's shed policy is actually observable at saturation
    # (with the serving default of 64 the runner could never fill the
    # pending queue and 'shed' would structurally read 0)
    broker = single_server_broker("lineitem", segments, max_pending=max_pending)
    queries = mixed_workload(segments)

    counters = {"errors": 0, "shed": 0, "quota": 0}
    clock = threading.Lock()  # target_qps drives run() from worker threads

    def run(pql: str) -> None:
        resp = broker.handle_pql(pql)
        if resp.exceptions:
            codes = {e.error_code for e in resp.exceptions}
            with clock:
                if ErrorCode.TOO_MANY_REQUESTS in codes:
                    counters["quota"] += 1
                elif ErrorCode.SERVER_SCHEDULER_DOWN in codes:
                    counters["shed"] += 1
                else:
                    counters["errors"] += 1

    def reset_counters() -> None:
        counters.update(errors=0, shed=0, quota=0)

    runner = QueryRunner(run)
    # warm every shape: staging + per-shape compile
    for q in queries:
        runner.single_thread([q], rounds=2)

    steps = []
    saturation = None
    for qps in qps_ladder:
        reset_counters()
        report = runner.target_qps(queries, qps=qps, duration_s=duration_s)
        rj = report.to_json()
        step = {
            "target_qps": qps,
            "achieved_qps": rj["qps"],
            "p50_ms": rj["p50Ms"],
            "p90_ms": rj["p90Ms"],
            "p99_ms": rj["p99Ms"],
            "queries": rj["numQueries"],
            "errors": counters["errors"],
            "shed": counters["shed"],
        }
        steps.append(step)
        print(json.dumps({"step": step}), flush=True)
        if saturation is None and (
            rj["qps"] < 0.9 * qps or counters["shed"] > 0 or counters["errors"] > 0
        ):
            saturation = qps

    # broker-tier overload demonstration: the per-table QPS quota is the
    # front-door shed (reference: broker rate limiting) — drive well
    # past a configured quota and record the 429-coded rejects
    quota_step = None
    if steps:
        quota_qps = max(4.0, qps_ladder[0])
        broker.quota.set_quota("lineitem", quota_qps)
        try:
            reset_counters()
            report = runner.target_qps(
                queries, qps=4 * quota_qps, duration_s=min(duration_s, 10.0)
            )
            rj = report.to_json()
            quota_step = {
                "quota_qps": quota_qps,
                "offered_qps": 4 * quota_qps,
                "answered_qps": round(
                    rj["qps"] - counters["quota"] / rj["wallSeconds"], 1
                ),
                "quota_rejects": counters["quota"],
                "shed": counters["shed"],
                "errors": counters["errors"],
            }
        finally:
            broker.quota.set_quota("lineitem", None)
        print(json.dumps({"quota_step": quota_step}), flush=True)

    server = broker.local_servers[0]
    return {
        "workload": "mixed: Q1 groupby scan, Q6 IN+range, selection needle, HLL groupby",
        "lane": None if server.lane is None else server.lane.stats(),
        "num_segments": len(segments),
        "total_rows": sum(s.num_docs for s in segments),
        "duration_s_per_step": duration_s,
        "overload_policy": "server tier: bounded FCFS queue, submits beyond "
        "max_pending shed immediately with error 210 (server/scheduler.py); "
        "broker tier: per-table QPS quota sheds with 429 (broker/quota.py)",
        "steps": steps,
        "quota_step": quota_step,
        "saturation_qps": saturation,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-segments", type=int, default=None)
    ap.add_argument("-rows-per-segment", type=int, default=None, dest="rps")
    ap.add_argument("-qps", type=str, default="2,4,8,16,32,64")
    ap.add_argument("-duration", type=float, default=15.0)
    ap.add_argument("-out", type=str, default="")
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    n_seg = args.segments if args.segments is not None else (16 if on_tpu else 2)
    rps = args.rps if args.rps is not None else (8_388_608 if on_tpu else 250_000)

    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    t0 = time.perf_counter()
    segments = [
        synthetic_lineitem_segment(rps, seed=11 + i, name=f"li{i}")
        for i in range(n_seg)
    ]
    print(json.dumps({"datagen_s": round(time.perf_counter() - t0, 1)}), flush=True)

    ladder = [float(x) for x in args.qps.split(",")]
    doc = run_curve(segments, ladder, args.duration)
    doc["platform"] = jax.devices()[0].platform
    out = json.dumps(doc, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()
