"""Serving curve under concurrent load: QPS vs latency + overload point.

The reference measures serving capacity by replaying queries at a target
QPS and recording latency percentiles (``QueryRunner.java:45-53``,
PinotResponseTime methodology).  This tool drives the full in-process
broker path (parse -> route -> scatter -> kernel -> reduce) with a MIXED
workload at a rising QPS ladder and records, per step:

  target QPS, achieved QPS, p50/p90/p99 ms, error count, shed count
  (scheduler saturation replies, error code 210), scheduler shed total

The saturation point is the first step where achieved QPS falls below
90% of target or queries start shedding.  Output: one JSON document
(stdout, and -out file) suitable for committing as the round's serving
curve artifact.

Usage:
  python -m pinot_tpu.tools.serving_curve                       # on-chip shape
  python -m pinot_tpu.tools.serving_curve -segments 2 \
      -rows-per-segment 250000 -qps 2,4,8 -duration 5           # CPU smoke
"""
from __future__ import annotations

import argparse
import json
import threading
import time
from typing import List

from pinot_tpu.common.response import ErrorCode


def mixed_workload(segments) -> List[str]:
    """The four BASELINE.md query shapes: flagship group-by scan (Q1),
    IN+range group-by (Q6-like), selective needle, HLL distinct."""
    d_price = segments[0].column("l_extendedprice").dictionary
    pv = d_price.get(d_price.cardinality // 2)
    return [
        "SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
        "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus TOP 10",
        "SELECT sum(l_extendedprice) FROM lineitem "
        "WHERE l_shipmode IN ('RAIL','FOB') AND "
        "l_receiptdate BETWEEN '1997-01-01' AND '1997-12-31' "
        "GROUP BY l_shipmode TOP 10",
        f"SELECT sum(l_quantity), count(*) FROM lineitem "
        f"WHERE l_extendedprice = {pv!r}",
        "SELECT distinctcounthll(l_shipdate) FROM lineitem "
        "GROUP BY l_returnflag TOP 10",
    ]


def run_curve(
    segments,
    qps_ladder: List[float],
    duration_s: float,
    max_pending: int = 24,
) -> dict:
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.query_runner import QueryRunner

    # max_pending BELOW the runner's 32-thread concurrency cap, so the
    # scheduler's shed policy is actually observable at saturation
    # (with the serving default of 64 the runner could never fill the
    # pending queue and 'shed' would structurally read 0)
    broker = single_server_broker("lineitem", segments, max_pending=max_pending)
    queries = mixed_workload(segments)

    counters = {"errors": 0, "shed": 0, "quota": 0}
    clock = threading.Lock()  # target_qps drives run() from worker threads

    def run(pql: str) -> None:
        resp = broker.handle_pql(pql)
        if resp.exceptions:
            codes = {e.error_code for e in resp.exceptions}
            with clock:
                if ErrorCode.TOO_MANY_REQUESTS in codes:
                    counters["quota"] += 1
                elif ErrorCode.SERVER_SCHEDULER_DOWN in codes:
                    counters["shed"] += 1
                else:
                    counters["errors"] += 1

    def reset_counters() -> None:
        counters.update(errors=0, shed=0, quota=0)

    runner = QueryRunner(run)
    # warm every shape: staging + per-shape compile
    for q in queries:
        runner.single_thread([q], rounds=2)

    steps = []
    saturation = None
    for qps in qps_ladder:
        reset_counters()
        report = runner.target_qps(queries, qps=qps, duration_s=duration_s)
        rj = report.to_json()
        step = {
            "target_qps": qps,
            "achieved_qps": rj["qps"],
            "p50_ms": rj["p50Ms"],
            "p90_ms": rj["p90Ms"],
            "p99_ms": rj["p99Ms"],
            "queries": rj["numQueries"],
            "errors": counters["errors"],
            "shed": counters["shed"],
        }
        steps.append(step)
        print(json.dumps({"step": step}), flush=True)
        if saturation is None and (
            rj["qps"] < 0.9 * qps or counters["shed"] > 0 or counters["errors"] > 0
        ):
            saturation = qps

    # broker-tier overload demonstration: the per-table QPS quota is the
    # front-door shed (reference: broker rate limiting) — drive well
    # past a configured quota and record the 429-coded rejects
    quota_step = None
    if steps:
        quota_qps = max(4.0, qps_ladder[0])
        broker.quota.set_quota("lineitem", quota_qps)
        try:
            reset_counters()
            report = runner.target_qps(
                queries, qps=4 * quota_qps, duration_s=min(duration_s, 10.0)
            )
            rj = report.to_json()
            quota_step = {
                "quota_qps": quota_qps,
                "offered_qps": 4 * quota_qps,
                "answered_qps": round(
                    rj["qps"] - counters["quota"] / rj["wallSeconds"], 1
                ),
                "quota_rejects": counters["quota"],
                "shed": counters["shed"],
                "errors": counters["errors"],
            }
        finally:
            broker.quota.set_quota("lineitem", None)
        print(json.dumps({"quota_step": quota_step}), flush=True)

    server = broker.local_servers[0]
    return {
        "workload": "mixed: Q1 groupby scan, Q6 IN+range, selection needle, HLL groupby",
        "lane": None if server.lane is None else server.lane.stats(),
        "num_segments": len(segments),
        "total_rows": sum(s.num_docs for s in segments),
        "duration_s_per_step": duration_s,
        "overload_policy": "server tier: bounded FCFS queue, submits beyond "
        "max_pending shed immediately with error 210 (server/scheduler.py); "
        "broker tier: per-table QPS quota sheds with 429 (broker/quota.py)",
        "steps": steps,
        "quota_step": quota_step,
        "saturation_qps": saturation,
    }


def run_two_tenant_ladder(
    segments_a,
    segments_b,
    qps_ladder: List[float],
    duration_s: float,
    quota_qps: float = 8.0,
    max_pending: int = 16,
    b_clients: int = 2,
) -> dict:
    """Two-tenant overload ladder: tenant A's offered QPS climbs the
    ladder (10x+ past its quota at the top) while tenant B holds a
    steady closed loop on the same server.  Per step, records each
    tenant's shed/quota counters and latency percentiles — the curve
    that shows WHERE A's overflow is shed (429 quota / 429 admission /
    210 fair-share) and that B's percentiles hold flat."""
    import threading as _threading

    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.routing import RoutingTableProvider
    from pinot_tpu.server.instance import ServerInstance
    from pinot_tpu.tools.query_runner import QueryRunner
    from pinot_tpu.transport.local import LocalTransport

    server = ServerInstance("benchServer", max_pending=max_pending)
    routing = RoutingTableProvider()
    for table, segs in (("tenantA", segments_a), ("tenantB", segments_b)):
        for seg in segs:
            server.add_segment(table, seg)
        routing.update(
            table, {s.segment_name: {"benchServer": "ONLINE"} for s in segs}
        )
    transport = LocalTransport()
    transport.register(("benchServer", 0), server.handle_request)
    broker = BrokerRequestHandler(
        transport,
        {"benchServer": ("benchServer", 0)},
        routing=routing,
        timeout_ms=30_000.0,
    )
    broker.quota.set_quota("tenantA", quota_qps)

    pql_a = "SELECT sum(l_quantity), count(*) FROM tenantA GROUP BY l_returnflag TOP 5"
    pql_b = "SELECT sum(l_extendedprice), count(*) FROM tenantB GROUP BY l_linestatus TOP 5"

    counters = {"a_quota": 0, "a_shed": 0, "a_error": 0, "a_ok": 0}
    clock = threading.Lock()

    def run_a(pql: str) -> None:
        resp = broker.handle_pql(pql)
        codes = {e.error_code for e in resp.exceptions}
        with clock:
            if not codes:
                counters["a_ok"] += 1
            elif ErrorCode.TOO_MANY_REQUESTS in codes:
                counters["a_quota"] += 1
            elif ErrorCode.SERVER_SCHEDULER_DOWN in codes:
                counters["a_shed"] += 1
            else:
                counters["a_error"] += 1

    runner = QueryRunner(run_a)
    for pql in (pql_a, pql_b):  # warm staging + compile for both shapes
        broker.handle_pql(pql)

    def admission_counts() -> dict:
        return {
            name.split(".", 1)[1]: broker.metrics.meter(name).count
            for name in (
                "admission.shedQuota",
                "admission.shedConcurrency",
                "admission.shedOverload",
            )
        }

    steps = []
    for qps in qps_ladder:
        with clock:
            counters.update(a_quota=0, a_shed=0, a_error=0, a_ok=0)
        adm_before = admission_counts()
        b_lat: List[float] = []
        b_errors = [0]
        stop = _threading.Event()

        def b_loop():
            while not stop.is_set():
                t0 = time.perf_counter()
                resp = broker.handle_pql(pql_b)
                ms = (time.perf_counter() - t0) * 1000.0
                with clock:
                    b_lat.append(ms)
                    if resp.exceptions:
                        b_errors[0] += 1

        b_threads = [
            _threading.Thread(target=b_loop, daemon=True) for _ in range(b_clients)
        ]
        for t in b_threads:
            t.start()
        report = runner.target_qps([pql_a], qps=qps, duration_s=duration_s)
        stop.set()
        for t in b_threads:
            t.join(timeout=10)
        rj = report.to_json()
        lat = sorted(b_lat)
        adm_after = admission_counts()
        steps.append(
            {
                "a_target_qps": qps,
                "a_offered_multiple": round(qps / quota_qps, 2),
                "a_achieved_qps": rj["qps"],
                "a_ok": counters["a_ok"],
                "a_quota_rejects": counters["a_quota"],
                "a_shed_210": counters["a_shed"],
                "a_errors": counters["a_error"],
                "admission_sheds": {
                    k: adm_after[k] - adm_before[k] for k in adm_after
                },
                "b_queries": len(lat),
                "b_errors": b_errors[0],
                "b_p50_ms": round(lat[len(lat) // 2], 3) if lat else 0.0,
                "b_p99_ms": round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 3)
                if lat
                else 0.0,
            }
        )
        print(json.dumps({"two_tenant_step": steps[-1]}), flush=True)

    return {
        "mode": "two-tenant-ladder",
        "quota_qps": quota_qps,
        "max_pending": max_pending,
        "overload_policy": "broker: adaptive admission (QPS bucket + "
        "per-table inflight + AIMD windows) sheds 429; server: per-table "
        "DRR fair-share queues shed 210 (see README Overload protection)",
        "steps": steps,
        "admission": broker.admission.snapshot(),
        "scheduler": server.scheduler.stats(),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-segments", type=int, default=None)
    ap.add_argument("-rows-per-segment", type=int, default=None, dest="rps")
    ap.add_argument("-qps", type=str, default="2,4,8,16,32,64")
    ap.add_argument("-duration", type=float, default=15.0)
    ap.add_argument("-out", type=str, default="")
    ap.add_argument(
        "-two-tenant",
        action="store_true",
        dest="two_tenant",
        help="two-tenant overload ladder: tenant A climbs the -qps ladder "
        "against its quota while tenant B runs a steady closed loop",
    )
    ap.add_argument("-quota-qps", type=float, default=8.0, dest="quota_qps")
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    n_seg = args.segments if args.segments is not None else (16 if on_tpu else 2)
    rps = args.rps if args.rps is not None else (8_388_608 if on_tpu else 250_000)

    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    t0 = time.perf_counter()
    segments = [
        synthetic_lineitem_segment(rps, seed=11 + i, name=f"li{i}")
        for i in range(n_seg)
    ]
    print(json.dumps({"datagen_s": round(time.perf_counter() - t0, 1)}), flush=True)

    ladder = [float(x) for x in args.qps.split(",")]
    if args.two_tenant:
        half = max(1, len(segments) // 2)
        doc = run_two_tenant_ladder(
            segments[:half],
            segments[half:] or segments[:half],
            ladder,
            args.duration,
            quota_qps=args.quota_qps,
        )
    else:
        doc = run_curve(segments, ladder, args.duration)
    doc["platform"] = jax.devices()[0].platform
    out = json.dumps(doc, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()
