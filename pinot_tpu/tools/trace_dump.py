"""Render a merged query trace as an ASCII waterfall.

Input is the ``traceInfo`` object a ``trace=true`` query returns
(``{"traceId": ..., "scopes": {scope: [span dicts]}}`` — see
``utils/trace.py`` for the span schema), either from a saved broker
response JSON / bare traceInfo JSON on disk or stdin, or fetched live
with ``--broker http://... --pql "SELECT ..."``.

Usage:
  python -m pinot_tpu.tools.trace_dump response.json
  python -m pinot_tpu.tools.trace_dump --broker http://127.0.0.1:8099 \\
      --pql "SELECT count(*) FROM myTable"

Output: one line per span, indented by tree depth, with a bar showing
the span's wall-clock window relative to the whole trace.  Broker and
server clocks are only as aligned as the hosts' NTP, so cross-process
offsets are approximate; durations are exact.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def _all_spans(trace_info: Dict[str, Any]) -> List[Dict[str, Any]]:
    scopes = trace_info.get("scopes")
    if scopes is None:
        # bare {scope: [spans]} shape (a server-side trace dict)
        scopes = {
            k: v for k, v in trace_info.items() if isinstance(v, list)
        }
    out: List[Dict[str, Any]] = []
    for scope, spans in scopes.items():
        for s in spans:
            out.append(dict(s, _scope=scope))
    return out


def render_waterfall(trace_info: Dict[str, Any], width: int = 40) -> str:
    """traceInfo -> multi-line ASCII waterfall (pure; unit-testable)."""
    spans = _all_spans(trace_info)
    if not spans:
        return "(empty trace)\n"
    by_id: Dict[Optional[str], Dict[str, Any]] = {s.get("id"): s for s in spans}
    children: Dict[Optional[str], List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in spans:
        parent = s.get("parent")
        if parent is not None and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)

    t0 = min(float(s.get("startMs") or 0.0) for s in spans)
    t1 = max(float(s.get("startMs") or 0.0) + float(s.get("ms") or 0.0) for s in spans)
    total = max(t1 - t0, 1e-9)

    def _key(s: Dict[str, Any]) -> Tuple[float, str]:
        return (float(s.get("startMs") or 0.0), str(s.get("id")))

    lines: List[str] = []
    trace_id = trace_info.get("traceId")
    header = f"trace {trace_id}  " if trace_id else ""
    lines.append(f"{header}total {total:.3f}ms  ({len(spans)} spans)")

    name_w = 44

    def _bar(start: float, dur: float) -> str:
        a = int((start - t0) / total * width)
        b = max(a + 1, int((start - t0 + dur) / total * width))
        a, b = min(a, width), min(b, width)
        return " " * a + "#" * (b - a) + " " * (width - b)

    def _emit(s: Dict[str, Any], depth: int) -> None:
        name = f"{'  ' * depth}{s.get('_scope')}:{s.get('span')}"
        if len(name) > name_w:
            name = name[: name_w - 1] + "…"
        start = float(s.get("startMs") or 0.0)
        dur = float(s.get("ms") or 0.0)
        tags = s.get("tags") or {}
        tag_str = (
            " " + ",".join(f"{k}={tags[k]}" for k in sorted(tags)) if tags else ""
        )
        lines.append(
            f"{name:<{name_w}} |{_bar(start, dur)}| "
            f"+{start - t0:9.3f}ms {dur:9.3f}ms{tag_str}"
        )
        for c in sorted(children.get(s.get("id"), ()), key=_key):
            _emit(c, depth + 1)

    for root in sorted(roots, key=_key):
        _emit(root, 0)
    return "\n".join(lines) + "\n"


def _load_trace(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Accept a full broker response JSON or a bare traceInfo."""
    if "traceInfo" in obj:
        return obj["traceInfo"]
    return obj


def render_cost(obj: Dict[str, Any]) -> str:
    """One-line cost-vector summary from a full broker response JSON
    (``cost`` + the scan stats) — empty string when the input is a bare
    traceInfo with no cost to show.  Pure; unit-testable."""
    if not isinstance(obj, dict) or "traceInfo" not in obj:
        return ""
    parts: List[str] = []
    for key, label in (
        ("numDocsScanned", "docs"),
        ("numEntriesScannedInFilter", "entriesInFilter"),
        ("numEntriesScannedPostFilter", "entriesPostFilter"),
    ):
        if key in obj:
            parts.append(f"{label}={obj[key]}")
    cost = obj.get("cost") or {}
    for key in sorted(cost):
        v = cost[key]
        if key == "bytesScanned":
            parts.append(f"bytes={v}")
        elif key.endswith("Ms"):
            parts.append(f"{key}={v}ms")
        else:
            parts.append(f"{key}={v}")
    if not parts:
        return ""
    return "cost: " + "  ".join(parts) + "\n"


def render_tiers(obj: Dict[str, Any]) -> str:
    """One-line serving-tier footer from a full broker response JSON:
    which tier served how many segments (the cost vector's segment
    counts) plus the plan-shape digest cross-linking this query to
    ``/debug/plans`` / ``/debug/workload``.  Empty for a bare traceInfo.
    Pure; unit-testable."""
    if not isinstance(obj, dict) or "traceInfo" not in obj:
        return ""
    from pinot_tpu.engine.results import SEGMENT_TIER_NAMES

    parts: List[str] = []
    cost = obj.get("cost") or {}
    for key, name in SEGMENT_TIER_NAMES.items():
        v = cost.get(key)
        if v:
            parts.append(f"{name}={int(v)}")
    digest = obj.get("planDigest")
    if digest:
        parts.append(f"planDigest={digest}")
    if not parts:
        return ""
    return "tiers: " + "  ".join(parts) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pinot_tpu-trace-dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("file", nargs="?", help="broker response / traceInfo JSON (default stdin)")
    p.add_argument("--broker", help="broker base URL: run --pql live with trace=true")
    p.add_argument("--pql", help="query to run against --broker")
    p.add_argument("--width", type=int, default=40, help="bar width in columns")
    args = p.parse_args(argv)

    if args.broker and args.pql:
        import urllib.request

        req = urllib.request.Request(
            args.broker.rstrip("/") + "/query",
            data=json.dumps({"pql": args.pql, "trace": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            obj = json.loads(r.read())
    elif args.file:
        with open(args.file) as f:
            obj = json.load(f)
    else:
        obj = json.load(sys.stdin)

    trace_info = _load_trace(obj)
    if not trace_info:
        print("no traceInfo in input (was the query run with trace=true?)", file=sys.stderr)
        return 1
    sys.stdout.write(render_waterfall(trace_info, width=args.width))
    # cost-vector footer: rows/bytes scanned, device vs host ms — the
    # "why was this slow" companion to the waterfall above
    sys.stdout.write(render_cost(obj))
    # tier-decision footer: which serving tier answered how many
    # segments, and the plan digest that cross-links to /debug/plans
    sys.stdout.write(render_tiers(obj))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
