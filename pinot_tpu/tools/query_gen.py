"""Random PQL query generator for differential testing.

The analog of the reference's ``QueryGenerator``
(pinot-integration-tests ``QueryGenerator.java:64``), which generates
random PQL + equivalent H2 SQL.  Here both engines (TPU + scan oracle)
speak PQL directly, so only PQL is generated; the oracle plays H2's role.
"""
from __future__ import annotations

import random
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence

from pinot_tpu.common.schema import DataType, FieldType, Schema

Row = Dict[str, Any]

_SV_AGGS = [
    "count", "sum", "min", "max", "avg", "minmaxrange", "distinctcount",
    "percentile50", "percentile90", "percentileest50", "percentileest95",
]


class QueryGenerator:
    def __init__(self, schema: Schema, rows: Sequence[Row], table: str = "testTable", seed: int = 0):
        self.schema = schema
        self.rows = list(rows)
        self.table = table
        self.rng = random.Random(seed)
        self.sv_dims = [
            s.name for s in schema.all_fields()
            if s.single_value and s.field_type != FieldType.METRIC
        ]
        self.mv_dims = [s.name for s in schema.all_fields() if not s.single_value]
        self.metrics = [s.name for s in schema.all_fields() if s.field_type == FieldType.METRIC]
        self.all_sv = [s.name for s in schema.all_fields() if s.single_value]

    # -- helpers -------------------------------------------------------
    def _sample_value(self, column: str) -> Any:
        row = self.rng.choice(self.rows)
        v = row[column]
        if isinstance(v, list):
            v = self.rng.choice(v)
        return v

    def _literal(self, column: str) -> str:
        v = self._sample_value(column)
        if isinstance(v, str):
            escaped = v.replace("'", "''")
            return f"'{escaped}'"
        return str(v)

    def _predicate_columns(self) -> List[str]:
        return self.all_sv + self.mv_dims

    def _predicate(self) -> str:
        col = self.rng.choice(self._predicate_columns())
        kind = self.rng.randrange(7)
        if kind == 6:
            v = self._sample_value(col)
            if isinstance(v, str) and v:
                # prefix regex from a live value: exercises the runs
                # eval kind and the regex-table caches
                pat = "^" + re.escape(v[: self.rng.randint(1, len(v))]) + ".*"
                escaped = pat.replace("'", "''")
                return f"regexp_like({col}, '{escaped}')"
            kind = self.rng.randrange(6)
        if kind == 0:
            return f"{col} = {self._literal(col)}"
        if kind == 1:
            return f"{col} <> {self._literal(col)}"
        if kind == 2:
            vals = ", ".join(self._literal(col) for _ in range(self.rng.randint(1, 4)))
            return f"{col} IN ({vals})"
        if kind == 3:
            vals = ", ".join(self._literal(col) for _ in range(self.rng.randint(1, 3)))
            return f"{col} NOT IN ({vals})"
        if kind == 4:
            a, b = self._literal(col), self._literal(col)
            if a.startswith("'"):
                lo, hi = sorted([a, b])
            else:
                lo, hi = sorted([a, b], key=float)
            return f"{col} BETWEEN {lo} AND {hi}"
        op = self.rng.choice(["<", ">", "<=", ">="])
        return f"{col} {op} {self._literal(col)}"

    def _where(self) -> str:
        n = self.rng.randrange(4)
        if n == 0:
            return ""
        preds = [self._predicate() for _ in range(n)]
        joined = preds[0]
        for p in preds[1:]:
            joined += f" {self.rng.choice(['AND', 'OR'])} {p}"
        return f" WHERE {joined}"

    # -- query kinds ---------------------------------------------------
    def aggregation_query(self) -> str:
        n = self.rng.randint(1, 3)
        aggs = []
        for _ in range(n):
            f = self.rng.choice(_SV_AGGS)
            if f == "count" and self.rng.random() < 0.5:
                aggs.append("count(*)")
            elif f == "distinctcount":
                # the MV variant (countmv/distinctcountmv naming, the
                # reference's *MVAggregationFunction family) sometimes
                if self.mv_dims and self.rng.random() < 0.3:
                    aggs.append(f"distinctcountmv({self.rng.choice(self.mv_dims)})")
                else:
                    aggs.append(f"distinctcount({self.rng.choice(self.all_sv)})")
            else:
                aggs.append(f"{f}({self.rng.choice(self.metrics)})")
        return f"SELECT {', '.join(aggs)} FROM {self.table}{self._where()}"

    def group_by_query(self) -> str:
        q = self.aggregation_query()
        k = self.rng.randint(1, 2)
        cols = self.rng.sample(self.sv_dims + self.mv_dims, k)
        top = self.rng.choice([5, 10, 50])
        return f"{q} GROUP BY {', '.join(cols)} TOP {top}"

    def selection_query(self) -> str:
        cols = self.rng.sample(self.all_sv, self.rng.randint(1, min(3, len(self.all_sv))))
        order = ""
        if self.rng.random() < 0.6:
            ocols = self.rng.sample(self.all_sv, self.rng.randint(1, 2))
            parts = [f"{c} {self.rng.choice(['ASC', 'DESC'])}" for c in ocols]
            order = f" ORDER BY {', '.join(parts)}"
        limit = self.rng.choice([5, 10, 25])
        return (
            f"SELECT {', '.join(cols)} FROM {self.table}{self._where()}{order} LIMIT {limit}"
        )

    def next_query(self) -> str:
        r = self.rng.random()
        if r < 0.4:
            return self.aggregation_query()
        if r < 0.8:
            return self.group_by_query()
        return self.selection_query()


# ---------------------------------------------------------------------------
# PQL + SQL pair generation for differential testing against SQLite
# (the reference generates PQL together with equivalent H2 SQL:
# pinot-integration-tests QueryGenerator.java generateH2Sql :311-426;
# SQLite plays H2's role here)
# ---------------------------------------------------------------------------

_SQL_AGG_FMT = {
    "count": "COUNT(*)",
    "sum": "SUM({c})",
    "min": "MIN({c})",
    "max": "MAX({c})",
    "avg": "AVG({c})",
    "minmaxrange": "(MAX({c}) - MIN({c}))",
    "distinctcount": "COUNT(DISTINCT {c})",
}


@dataclass
class DiffQuery:
    """One generated query in both dialects plus the structure the
    comparator needs to interpret results."""

    pql: str
    kind: str  # "agg" | "groupby" | "selection"
    where: str  # "" or " WHERE ..." — valid in both PQL and SQLite
    aggs: List[tuple] = field(default_factory=list)  # (func, col)
    group_cols: List[str] = field(default_factory=list)
    top: int = 0
    select_cols: List[str] = field(default_factory=list)
    order_by: List[tuple] = field(default_factory=list)  # (col, ascending)
    limit: int = 0

    def agg_sql_exprs(self) -> List[str]:
        return [_SQL_AGG_FMT[f].format(c=c) for f, c in self.aggs]


class SqlDiffQueryGenerator(QueryGenerator):
    """Generates (PQL, SQLite-SQL) pairs over the SQL-translatable query
    subset: single-value columns only, exact-arithmetic predicate columns
    (STRING/INT/LONG — FLOAT columns are stored float32 on device, so
    equality/order against SQLite's float64 would diff spuriously), and
    the aggregation functions SQLite can express."""

    _DIFF_AGGS = ["count", "sum", "min", "max", "avg", "minmaxrange", "distinctcount"]

    def __init__(self, schema: Schema, rows: Sequence[Row], table: str = "testTable", seed: int = 0):
        super().__init__(schema, rows, table, seed)
        exact = (DataType.STRING, DataType.INT, DataType.LONG)
        self.exact_cols = [
            s.name
            for s in schema.all_fields()
            if s.single_value and s.data_type in exact
        ]

    def _predicate_columns(self) -> List[str]:
        return self.exact_cols

    def _aggs(self) -> List[tuple]:
        out = []
        for _ in range(self.rng.randint(1, 3)):
            f = self.rng.choice(self._DIFF_AGGS)
            if f == "count":
                out.append(("count", "*"))
            elif f == "distinctcount":
                out.append((f, self.rng.choice(self.exact_cols)))
            else:
                out.append((f, self.rng.choice(self.metrics)))
        return out

    def _agg_pql(self, aggs: List[tuple]) -> str:
        return ", ".join("count(*)" if f == "count" else f"{f}({c})" for f, c in aggs)

    def agg_diff(self) -> DiffQuery:
        aggs = self._aggs()
        where = self._where()
        return DiffQuery(
            pql=f"SELECT {self._agg_pql(aggs)} FROM {self.table}{where}",
            kind="agg",
            where=where,
            aggs=aggs,
        )

    def group_by_diff(self) -> DiffQuery:
        aggs = self._aggs()
        where = self._where()
        dims = [c for c in self.exact_cols]
        cols = self.rng.sample(dims, self.rng.randint(1, 2))
        top = self.rng.choice([3, 10, 50])
        return DiffQuery(
            pql=(
                f"SELECT {self._agg_pql(aggs)} FROM {self.table}{where} "
                f"GROUP BY {', '.join(cols)} TOP {top}"
            ),
            kind="groupby",
            where=where,
            aggs=aggs,
            group_cols=cols,
            top=top,
        )

    def selection_diff(self) -> DiffQuery:
        cols = self.rng.sample(self.exact_cols, self.rng.randint(1, min(3, len(self.exact_cols))))
        order: List[tuple] = []
        order_sql = ""
        if self.rng.random() < 0.6:
            ocols = self.rng.sample(cols, self.rng.randint(1, min(2, len(cols))))
            order = [(c, self.rng.random() < 0.5) for c in ocols]
            order_sql = " ORDER BY " + ", ".join(
                f"{c} {'ASC' if asc else 'DESC'}" for c, asc in order
            )
        limit = self.rng.choice([5, 10, 25])
        where = self._where()
        return DiffQuery(
            pql=(
                f"SELECT {', '.join(cols)} FROM {self.table}{where}{order_sql} LIMIT {limit}"
            ),
            kind="selection",
            where=where,
            select_cols=cols,
            order_by=order,
            limit=limit,
        )

    def next_diff(self) -> DiffQuery:
        r = self.rng.random()
        if r < 0.35:
            return self.agg_diff()
        if r < 0.7:
            return self.group_by_diff()
        return self.selection_diff()
