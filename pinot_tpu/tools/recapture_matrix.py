"""One-command filter-matrix re-capture (r17).

Re-measures the four-tier filter matrix at the committed capture's
workload shape, writes the perf_gate-ready document, and (when a
committed baseline exists) prints the gate verdict against it — the
whole re-capture ritual in one invocation:

  JAX_PLATFORMS=cpu python -m pinot_tpu.tools.recapture_matrix
  python -m pinot_tpu.tools.recapture_matrix --out FILTER_MATRIX_CPU_r17.json

Defaults reproduce the committed CPU capture shape (2 segments x 250k
rows, 15 reps); pass the knobs through to scale up on a real device.
The written document is what ``tools/perf_gate.py`` gates CI with
(kind ``filtermatrix_*`` — tier win counts, not latencies).
"""
from __future__ import annotations

import argparse
import json
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="pinot_tpu-recapture-matrix", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("-segments", type=int, default=None)
    ap.add_argument("-rows-per-segment", type=int, default=None, dest="rps")
    ap.add_argument("-reps", type=int, default=15)
    ap.add_argument(
        "--out",
        default="FILTER_MATRIX_CPU_r17.json",
        help="capture path to (over)write",
    )
    ap.add_argument(
        "--no-gate",
        action="store_true",
        help="skip the perf_gate comparison against the committed capture",
    )
    args = ap.parse_args()

    import jax

    from pinot_tpu.tools.datagen import synthetic_lineitem_segment
    from pinot_tpu.tools.filter_matrix import run_matrix

    on_tpu = jax.default_backend() not in ("cpu",)
    n_seg = args.segments if args.segments is not None else (16 if on_tpu else 2)
    rps = args.rps if args.rps is not None else (8_388_608 if on_tpu else 250_000)

    t0 = time.perf_counter()
    segments = [
        synthetic_lineitem_segment(rps, seed=11 + i, name=f"li{i}")
        for i in range(n_seg)
    ]
    print(json.dumps({"datagen_s": round(time.perf_counter() - t0, 1)}), flush=True)

    doc = run_matrix(segments, args.reps)
    doc["platform"] = jax.devices()[0].platform
    doc["metric"] = f"filtermatrix_{doc['platform']}"
    doc["value"] = doc["bitsliced_midsel_wins"]

    gate_rc = 0
    if not args.no_gate and os.path.exists(args.out):
        # gate the fresh run against the capture we are about to replace
        from pinot_tpu.tools.perf_gate import compare, load_bench

        verdict = compare(load_bench(args.out), doc)
        print(json.dumps(verdict, indent=1))
        gate_rc = 1 if verdict["verdict"] == "fail" else 0

    with open(args.out, "w") as f:
        f.write(json.dumps(doc, indent=1) + "\n")
    print(json.dumps({"wrote": args.out, "tier_wins": doc["tier_wins"]}))
    return gate_rc


if __name__ == "__main__":
    raise SystemExit(main())
