"""Micro benchmarks for individual engine components.

The pinot-perf JMH analog — one entry per reference benchmark class:

  bitpack    -> ForwardIndexReaderBenchmark.java:42 (fixed-bit codec)
  dictionary -> StringDictionaryPerfTest.java:46 (lookup throughput)
  filter     -> FilterOperatorBenchmark.java:51 (predicate over a segment)
  groupby    -> BenchmarkQueryEngine.java:50 (aggregation group-by kernel)
  realtime   -> BenchmarkRealtimeConsumptionSpeed.java:38 (index() rate)
  csv        -> ingest pipeline (columnar vs row-wise build)

Run: ``python -m pinot_tpu.tools.microbench [name ...] [-rows N]``.
Each benchmark prints one JSON line: {"bench", "value", "unit", detail}.
"""
from __future__ import annotations

import argparse
import json
import time
from typing import Callable, Dict, List

import numpy as np


def _time_best(fn: Callable[[], object], repeat: int = 5) -> float:
    """Best-of-N wall seconds (JMH SampleTime-ish, minus the forks)."""
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_bitpack(rows: int) -> Dict:
    from pinot_tpu.segment.bitpack import bits_required, pack_bits, unpack_bits

    rng = np.random.default_rng(7)
    vals = rng.integers(0, 4097, size=rows).astype(np.int32)
    nbits = bits_required(4097)
    packed = pack_bits(vals, nbits)
    t_pack = _time_best(lambda: pack_bits(vals, nbits))
    t_unpack = _time_best(lambda: unpack_bits(packed, nbits, rows))
    return {
        "bench": "bitpack",
        "value": round(rows / t_unpack / 1e6, 1),
        "unit": "M vals/s unpack",
        "detail": {"packMps": round(rows / t_pack / 1e6, 1), "nbits": nbits},
    }


def bench_dictionary(rows: int) -> Dict:
    from pinot_tpu.common.schema import DataType
    from pinot_tpu.segment.dictionary import Dictionary

    rng = np.random.default_rng(11)
    card = 100_000
    values = [f"value_{i:08d}" for i in range(card)]
    d = Dictionary(DataType.STRING, values)
    probe = [values[i] for i in rng.integers(0, card, size=10_000)]
    t_lookup = _time_best(lambda: [d.index_of(v) for v in probe])
    arr = np.asarray(
        [values[i] for i in rng.integers(0, card, size=rows)], dtype=object
    )
    t_index = _time_best(lambda: d.index_array(arr))
    return {
        "bench": "dictionary",
        "value": round(len(probe) / t_lookup / 1e3, 1),
        "unit": "K lookups/s",
        "detail": {"indexArrayMps": round(rows / t_index / 1e6, 2), "card": card},
    }


def _engine_fixture(rows: int):
    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.segment.columnar import build_segment_from_columns
    from pinot_tpu.tools.datagen import make_test_schema

    rng = np.random.default_rng(13)
    schema = make_test_schema(with_mv=False)
    cols = {
        "dimStr": np.asarray(
            [f"s{i}" for i in rng.integers(0, 50, size=rows)], dtype=object
        ),
        "dimInt": rng.integers(0, 1000, size=rows).astype(np.int32),
        "dimLong": rng.integers(0, 10_000, size=rows).astype(np.int64),
        "metInt": rng.integers(0, 10_000, size=rows).astype(np.int32),
        "metFloat": rng.random(rows, dtype=np.float32),
        "metDouble": rng.random(rows, dtype=np.float64),
        "daysSinceEpoch": rng.integers(17000, 17100, size=rows).astype(np.int32),
    }
    seg = build_segment_from_columns(schema, cols, rows, "mb", "mb0")
    return QueryExecutor(), [seg]


def _bench_query(executor, segments, pql: str, rows: int, name: str) -> Dict:
    from pinot_tpu.pql import parse_pql

    req = parse_pql(pql)
    executor.execute(segments, req)  # compile / warm
    t = _time_best(lambda: executor.execute(segments, req))
    return {
        "bench": name,
        "value": round(rows / t / 1e6, 1),
        "unit": "M rows/s",
        "detail": {"medianMs": round(t * 1000, 3), "pql": pql},
    }


def bench_filter(rows: int) -> Dict:
    ex, segs = _engine_fixture(rows)
    return _bench_query(
        ex,
        segs,
        "SELECT count(*) FROM testTable WHERE dimInt > 100 AND dimInt <= 900",
        rows,
        "filter",
    )


def bench_groupby(rows: int) -> Dict:
    ex, segs = _engine_fixture(rows)
    return _bench_query(
        ex,
        segs,
        "SELECT sum(metInt), max(metDouble) FROM testTable GROUP BY dimStr TOP 10",
        rows,
        "groupby",
    )


def bench_realtime(rows: int) -> Dict:
    from pinot_tpu.realtime.mutable import MutableSegment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=False)
    data = random_rows(schema, min(rows, 200_000), seed=5)

    def consume():
        # consumers fetch in batches (netstream/kafka fetch sizes);
        # index_batch is the production ingest call
        seg = MutableSegment(schema, "rt0", "rt")
        for i in range(0, len(data), 500):
            seg.index_batch(data[i : i + 500])
        return seg

    t = _time_best(consume, repeat=3)
    return {
        "bench": "realtime",
        "value": round(len(data) / t / 1e3, 1),
        "unit": "K rows/s indexed",
        "detail": {"rows": len(data)},
    }


def bench_csv(rows: int) -> Dict:
    import os
    import tempfile

    from pinot_tpu.segment.columnar import build_segment_from_csv
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    schema = make_test_schema(with_mv=False)
    data = random_rows(schema, rows, seed=3)
    names = [s.name for s in schema.all_fields()]
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "d.csv")
        with open(path, "w") as f:
            f.write(",".join(names) + "\n")
            for row in data:
                f.write(",".join(str(row[n]) for n in names) + "\n")
        t = _time_best(lambda: build_segment_from_csv(schema, path, "t", "b"), repeat=3)
    return {
        "bench": "csv",
        "value": round(rows / t / 1e3, 1),
        "unit": "K rows/s ingested",
        "detail": {"rows": rows},
    }


BENCHES: Dict[str, Callable[[int], Dict]] = {
    "bitpack": bench_bitpack,
    "dictionary": bench_dictionary,
    "filter": bench_filter,
    "groupby": bench_groupby,
    "realtime": bench_realtime,
    "csv": bench_csv,
}


def run(names: List[str], rows: int) -> List[Dict]:
    out = []
    for name in names:
        out.append(BENCHES[name](rows))
        print(json.dumps(out[-1]), flush=True)
    return out


def main() -> None:
    ap = argparse.ArgumentParser("pinot_tpu-microbench")
    ap.add_argument("benches", nargs="*", default=[], help=f"subset of {list(BENCHES)}")
    ap.add_argument("-rows", type=int, default=1_000_000)
    args = ap.parse_args()
    names = args.benches or list(BENCHES)
    for n in names:
        if n not in BENCHES:
            raise SystemExit(f"unknown bench {n!r}; choose from {list(BENCHES)}")
    run(names, args.rows)




def bench_staging_ab(rows: int) -> Dict:
    """A/B the agg-column staging policy on the current backend: narrow
    fwd + in-kernel dictionary gather vs dictionary-decoded float raw
    stream, on the TPC-H-Q1 kernel shape.  Run on the real chip to pick
    RAW_CARD_MIN (config.py); the gather's VMEM-table cost vs the raw
    stream's 2-4x HBM bytes is hardware-dependent."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import segment_arrays, stage_segments
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    segs = [synthetic_lineitem_segment(rows, seed=31 + i, name=f"ab{i}") for i in range(2)]
    pql = ("SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
           "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
           "GROUP BY l_returnflag, l_linestatus TOP 10")
    request = optimize_request(parse_pql(pql))
    ctx = get_table_context(segs)
    needed = sorted(set(request.referenced_columns()))

    def run_mode(raw_cols):
        staged = stage_segments(
            segs, needed, raw_columns=raw_cols,
            gfwd_columns=("l_returnflag", "l_linestatus"), ctx=ctx,
        )
        plan = build_static_plan(request, ctx, staged)
        q = build_query_inputs(request, plan, ctx, staged)

        def conv(x):
            if isinstance(x, np.ndarray):
                return jnp.asarray(x)
            if isinstance(x, list):
                return [conv(v) for v in x]
            if isinstance(x, dict):
                return {k: conv(v) for k, v in x.items()}
            return x

        qi = conv(q)
        arrays = segment_arrays(staged, needed)
        kernel = make_table_kernel(plan)
        # sync via device_get of the FULL output tree: on the tunneled
        # runtime block_until_ready (and readiness of aliased leaves
        # like the passed-through num_docs) can report before the
        # aggregations finish — only a D2H transfer is a true barrier.
        # The stream is FIFO, so fetching the last dispatch covers all.
        jax.device_get(kernel(arrays, qi))  # compile
        n = 10
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = kernel(arrays, qi)
        jax.device_get(out)
        return (time.perf_counter() - t0) / n * 1000

    gather_ms = run_mode(())
    raw_ms = run_mode(("l_quantity", "l_extendedprice", "l_discount"))
    total = rows * 2
    return {
        "name": "staging_ab_q1",
        "rows": total,
        "gather_ms": round(gather_ms, 3),
        "raw_ms": round(raw_ms, 3),
        "gather_rows_per_sec": round(total / (gather_ms / 1000), 1),
        "raw_rows_per_sec": round(total / (raw_ms / 1000), 1),
    }


BENCHES["staging_ab"] = bench_staging_ab



def bench_pallas_ab(rows: int) -> Dict:
    """Pallas fused Q1 kernel vs the production XLA table kernel on one
    segment (VERDICT r2 #4: commit the wiring decision with data).

    Both sides read the same arrays: interval filter on the date fwd,
    three raw float32 value feeds, 12-bucket one-hot matmul group-by.
    The XLA side is the actual serving kernel (make_table_kernel); the
    pallas side is engine/pallas_kernels.fused_filtered_groupby_sums.
    On CPU the pallas kernel only runs in interpret mode (orders of
    magnitude slow) — run this on the real chip.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pinot_tpu.engine.context import get_table_context
    from pinot_tpu.engine.device import segment_arrays, stage_segments
    from pinot_tpu.engine.kernel import make_table_kernel
    from pinot_tpu.engine.pallas_kernels import (
        PALLAS_AVAILABLE,
        fused_filtered_groupby_sums,
    )
    from pinot_tpu.engine.plan import build_query_inputs, build_static_plan
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    if not PALLAS_AVAILABLE:
        return {"name": "pallas_ab_q1", "error": "pallas unavailable"}
    interpret = jax.default_backend() == "cpu"

    seg = synthetic_lineitem_segment(rows, seed=41, name="pab0")
    pql = ("SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), count(*) "
           "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
           "GROUP BY l_returnflag, l_linestatus TOP 10")
    request = optimize_request(parse_pql(pql))
    ctx = get_table_context([seg])
    needed = sorted(set(request.referenced_columns()))
    agg_cols = ("l_quantity", "l_extendedprice", "l_discount")
    staged = stage_segments(
        [seg], needed, raw_columns=agg_cols,
        gfwd_columns=("l_returnflag", "l_linestatus"), ctx=ctx,
    )
    plan = build_static_plan(request, ctx, staged)
    q = build_query_inputs(request, plan, ctx, staged)

    def timed(fn, n=10):
        jax.device_get(fn())  # compile; D2H is the only true barrier here
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        jax.device_get(out)
        return (time.perf_counter() - t0) / n * 1000

    # XLA side: the serving kernel
    from pinot_tpu.engine.device import to_device_inputs

    qi = to_device_inputs(q)
    arrays = segment_arrays(staged, needed)
    xla_kernel = make_table_kernel(plan)
    xla_ms = timed(lambda: xla_kernel(arrays, qi))

    # pallas side: same arrays, fused single pass
    fwd = jnp.asarray(staged.columns["l_shipdate"].fwd[0].astype(np.int32))
    lo, hi = (int(v) for v in np.asarray(q["bounds"][0][0]))
    valid = jnp.ones(rows, dtype=bool)
    rf = staged.columns["l_returnflag"].gfwd[0].astype(np.int32)
    ls = staged.columns["l_linestatus"].gfwd[0].astype(np.int32)
    ls_card = ctx.column("l_linestatus").global_cardinality
    keys = jnp.asarray(rf * ls_card + ls)
    raws = [jnp.asarray(staged.columns[c].raw[0]) for c in agg_cols]
    capacity = ctx.column("l_returnflag").global_cardinality * ls_card

    fused = jax.jit(
        lambda f, v, k, r0, r1, r2: fused_filtered_groupby_sums(
            f, None, v, k, [None] * 3, [None] * 3, capacity,
            interpret=interpret, filter_bounds=(lo, hi), value_raws=[r0, r1, r2],
        )
    )
    pallas_ms = timed(lambda: fused(fwd, valid, keys, *raws))

    # cross-check: both paths must agree on matched docs and the total
    # grouped count before the timing comparison means anything
    xo = jax.device_get(xla_kernel(arrays, qi))
    po = jax.device_get(fused(fwd, valid, keys, *raws))
    pallas_docs = float(po[0])
    xla_docs = float(np.asarray(xo["num_docs"]).sum())
    agree = abs(pallas_docs - xla_docs) < 0.5 and abs(
        float(np.asarray(po[1]).sum()) - pallas_docs
    ) < 0.5

    return {
        "name": "pallas_ab_q1",
        "rows": rows,
        "xla_ms": round(xla_ms, 3),
        "pallas_ms": round(pallas_ms, 3),
        "xla_rows_per_sec": round(rows / (xla_ms / 1000), 1),
        "pallas_rows_per_sec": round(rows / (pallas_ms / 1000), 1),
        "matched_docs": pallas_docs,
        "paths_agree": bool(agree),
    }


BENCHES["pallas_ab"] = bench_pallas_ab


def bench_qinput_cache_ab(rows: int) -> Dict:
    """Per-query serving cost with vs without the device-resident
    query-input cache (executor._qinput_cache): on a tunneled chip the
    upload it skips is a full host->device round trip per query.  Runs
    the SAME Q1-shaped query through the executor repeatedly, once with
    the cache cleared before every query and once warm."""
    import time as _time

    from pinot_tpu.engine.executor import QueryExecutor
    from pinot_tpu.engine.reduce import reduce_to_response
    from pinot_tpu.pql import optimize_request, parse_pql
    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    seg_rows = max(rows // 4, 1)
    segments = [
        synthetic_lineitem_segment(seg_rows, seed=61 + i, name=f"qc{i}")
        for i in range(4)
    ]
    pql = (
        "SELECT sum(l_quantity), sum(l_extendedprice), count(*) FROM lineitem "
        "WHERE l_shipdate <= '1998-09-02' GROUP BY l_returnflag, l_linestatus TOP 10"
    )
    ex = QueryExecutor()

    def one() -> None:
        req = optimize_request(parse_pql(pql))
        reduce_to_response(req, [ex.execute(segments, req)])

    one()  # stage + compile
    n = 15

    # cold first, then warm, then a second cold pass — reporting the
    # BEST cold so steady-state drift can't masquerade as cache effect
    def cold_pass() -> float:
        t0 = _time.perf_counter()
        for _ in range(n):
            ex._qinput_cache.clear()
            ex._qinput_cache_bytes = 0
            one()
        return (_time.perf_counter() - t0) / n * 1000

    c1 = cold_pass()
    t0 = _time.perf_counter()
    for _ in range(n):
        one()
    warm_ms = (_time.perf_counter() - t0) / n * 1000
    cold_ms = min(c1, cold_pass())

    return {
        "bench": "qinput_cache_ab",
        "value": round(cold_ms - warm_ms, 3),
        "unit": "ms saved/query",
        "detail": {
            "rows": seg_rows * 4,
            "warm_ms_per_query": round(warm_ms, 3),
            "cold_ms_per_query": round(cold_ms, 3),
        },
    }


BENCHES["qinput_cache_ab"] = bench_qinput_cache_ab




def bench_hll_lowerings(rows: int) -> Dict:
    """A/B the grouped-HLL lowerings at the north-star register shape
    (capacity 1024, HLL_M=256): the r4 serialized scatter-max vs the r5
    packed int32 sort + searchsorted run-max (tools/probe_hll_e2e.py
    measured 12.4 vs 4.2 ns/row on v5e), plus the factored one-hot
    contraction vs the old M=1 form at the bench presence shape
    (K=2^14: 31.5 vs 0.8 ns/row on v5e).  Verifies bit-identical
    registers between scatter and sort."""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.engine import config as engine_config
    from pinot_tpu.engine.kernel import _reduce_hll_sort, _value_state_counts

    rng = np.random.default_rng(3)
    cap, m = 1024, engine_config.HLL_M
    gid = rng.integers(0, cap, size=rows).astype(np.int32)
    bucket = rng.integers(0, m, size=rows).astype(np.int32)
    rho = np.minimum(1 + rng.geometric(0.5, size=rows), 40).astype(np.int32)
    packed = jnp.asarray(((gid * m + bucket) << 6) | rho)
    flat = jnp.asarray(gid * m + bucket)
    rho_u8 = jnp.asarray(rho.astype(np.uint8))

    def fetch(x):
        np.asarray(x)

    def scatter(fl, rh):
        return jnp.zeros(cap * m, jnp.uint8).at[fl].max(rh, mode="drop").reshape(cap, m)

    f_sort = jax.jit(lambda p: _reduce_hll_sort(p, cap))
    f_scat = jax.jit(scatter)
    fetch(f_sort(packed))
    fetch(f_scat(flat, rho_u8))
    t_sort = _time_best(lambda: fetch(f_sort(packed)))
    t_scat = _time_best(lambda: fetch(f_scat(flat, rho_u8)))
    identical = bool(
        (np.asarray(f_sort(packed)) == np.asarray(f_scat(flat, rho_u8))).all()
    )

    K = 1 << 14  # bench presence shape
    idx = jnp.asarray(rng.integers(0, K, size=rows).astype(np.int32))
    # time the XLA body DIRECTLY (bypassing the env gate) so the A/B
    # keeps its baseline even when PINOT_TPU_VALUE_STATE_PALLAS=1
    from pinot_tpu.engine.kernel import _value_state_counts_xla

    f_fac = jax.jit(lambda i: _value_state_counts_xla(i, K))
    fetch(f_fac(idx))
    t_fac = _time_best(lambda: fetch(f_fac(idx)))
    try:
        from pinot_tpu.engine.kernel import _value_state_counts_pallas

        f_pal = jax.jit(lambda i: _value_state_counts_pallas(i, K))
        fetch(f_pal(idx))
        t_pal = _time_best(lambda: fetch(f_pal(idx)))
        pallas_agrees = bool(
            (np.asarray(f_pal(idx)) == np.asarray(f_fac(idx))).all()
        )
    except Exception as e:  # pallas lowering unavailable on this backend
        t_pal, pallas_agrees = None, f"{type(e).__name__}: {e}"

    return {
        "bench": "hll_lowerings",
        "value": round(t_scat / max(t_sort, 1e-9), 2),
        "unit": "x sort-vs-scatter speedup",
        "detail": {
            "rows": rows,
            "sort_ms": round(t_sort * 1e3, 2),
            "scatter_ms": round(t_scat * 1e3, 2),
            "factored_contraction_K16384_ms": round(t_fac * 1e3, 2),
            "pallas_contraction_K16384_ms": (
                round(t_pal * 1e3, 2) if isinstance(t_pal, float) else t_pal
            ),
            "pallas_agrees": pallas_agrees,
            "registers_bit_identical": identical,
            "platform": jax.devices()[0].platform,
        },
    }


BENCHES["hll_lowerings"] = bench_hll_lowerings


if __name__ == "__main__":
    main()
