"""Star-tree build cost at real scale (r4 VERDICT #5).

The reference's builder is off-heap specifically to build trees over
huge segments (``OffHeapStarTreeBuilder.java:96``).  Here the builder
is vectorized numpy and runs PER SEGMENT — a 67M-row table builds as
8 independent 8.4M-row builds, so peak RSS is bounded by one segment's
working set regardless of table size (the streaming property the
reference gets from going off-heap).

Measures, for the two committed cube configs (the north-star HLL cube
and the baseball cube):
  - per-segment and total build wall time over >= 67M rows,
  - peak RSS across the build,
  - query p50 through the broker with trees attached vs detached
    (the speedup the build cost buys).

Usage:
  python -m pinot_tpu.tools.startree_scale            # 8 x 8.4M rows
  python -m pinot_tpu.tools.startree_scale -segments 2 -rows 500000
"""
from __future__ import annotations

import argparse
import json
import resource
import time
from typing import List


def _peak_rss_gb() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1048576, 2)


def _p50(broker, pql: str, n: int) -> float:
    times: List[float] = []
    for _ in range(n):
        t0 = time.perf_counter()
        resp = broker.handle_pql(pql)
        assert not resp.exceptions, resp.exceptions
        times.append((time.perf_counter() - t0) * 1000)
    times.sort()
    return round(times[len(times) // 2], 1)


def run_config(name, segments, schema, tree_config, table, pql, reps) -> dict:
    from pinot_tpu.startree.builder import build_star_tree
    from pinot_tpu.tools.cluster_harness import single_server_broker

    build_times = []
    for seg in segments:
        t0 = time.perf_counter()
        build_star_tree(seg, schema, tree_config)
        build_times.append(time.perf_counter() - t0)
    total_rows = sum(s.num_docs for s in segments)
    doc = {
        "config": name,
        "total_rows": total_rows,
        "num_segments": len(segments),
        "tree_build_total_s": round(sum(build_times), 1),
        "tree_build_per_segment_s": round(max(build_times), 1),
        "tree_records_per_segment": segments[0].metadata.custom["starTree"]["numRecords"],
        "peak_rss_gb": _peak_rss_gb(),
        "pql": pql,
    }
    broker = single_server_broker(table, segments)
    _p50(broker, pql, 1)  # warm + compile
    doc["startree_p50_ms"] = _p50(broker, pql, reps)
    trees = [s.star_tree for s in segments]
    for s in segments:
        s.star_tree = None
    doc["scan_p50_ms"] = _p50(broker, pql, max(3, reps // 3))
    for s, t in zip(segments, trees):
        s.star_tree = t
    doc["speedup"] = round(doc["scan_p50_ms"] / max(doc["startree_p50_ms"], 1e-3), 1)
    print(json.dumps(doc), flush=True)
    return doc


def run_one(config_name: str, segments_n: int, rows: int, reps: int) -> dict:
    from pinot_tpu.startree.builder import StarTreeBuilderConfig
    from pinot_tpu.tools.datagen import (
        adevents_schema,
        baseball_schema,
        synthetic_adevents_segment,
        synthetic_baseball_segment,
    )

    t0 = time.perf_counter()
    if config_name == "adevents_hll_cube":
        segs = [
            synthetic_adevents_segment(rows, seed=100 + i, name=f"sta{i}")
            for i in range(segments_n)
        ]
        gen_s = round(time.perf_counter() - t0, 1)
        doc = run_config(
            config_name,
            segs,
            adevents_schema(),
            StarTreeBuilderConfig(
                split_order=["campaign_id", "site_id"],
                hll_columns=["user_id"],
                max_leaf_records=64,
            ),
            "adevents",
            "SELECT distinctcounthll(user_id) FROM adevents GROUP BY campaign_id TOP 10",
            reps,
        )
    else:
        segs = [
            synthetic_baseball_segment(rows, seed=200 + i, name=f"stb{i}")
            for i in range(segments_n)
        ]
        gen_s = round(time.perf_counter() - t0, 1)
        doc = run_config(
            config_name,
            segs,
            baseball_schema(),
            StarTreeBuilderConfig(),
            "baseballStats",
            "SELECT sum(runs), count(*) FROM baseballStats GROUP BY teamID TOP 20",
            reps,
        )
    doc["datagen_s"] = gen_s
    return doc


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-segments", type=int, default=8)
    ap.add_argument("-rows", type=int, default=8_388_608, help="rows per segment")
    ap.add_argument("-reps", type=int, default=9)
    ap.add_argument("-only", type=str, default="", help="(internal) run one config")
    ap.add_argument("-out", type=str, default="")
    args = ap.parse_args()

    if args.only:
        # subprocess mode: ru_maxrss is a process-lifetime high-water
        # mark, so each config runs in its OWN process for an honest
        # per-config peak
        print("RESULT " + json.dumps(run_one(args.only, args.segments, args.rows, args.reps)))
        return

    import os
    import subprocess
    import sys

    import jax

    docs = {}
    for name in ("adevents_hll_cube", "baseball_cube"):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "pinot_tpu.tools.startree_scale",
                "-only",
                name,
                "-segments",
                str(args.segments),
                "-rows",
                str(args.rows),
                "-reps",
                str(args.reps),
            ],
            capture_output=True,
            text=True,
            env={**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
        )
        lines = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(f"{name} failed: {proc.stderr[-1500:]}")
        docs[name] = json.loads(lines[-1][len("RESULT ") :])

    out = {
        "platform": jax.devices()[0].platform,
        **docs,
        "note": "per-segment builds bound peak RSS by one segment's working "
        "set (streaming property); build wall scales linearly with segments; "
        "each config measured in its own process (honest per-config peak RSS)",
    }
    text = json.dumps(out, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
