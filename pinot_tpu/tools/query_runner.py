"""Query perf runner: singleThread / multiThreads / targetQPS modes.

The ``pinot-perf`` harness analog (``QueryRunner.java:42``, modes
:45-53): replays a list of PQL queries against a query function or a
broker URL, reporting throughput and latency percentiles (:115-117).
"""
from __future__ import annotations

import concurrent.futures
import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence


@dataclass
class RunnerReport:
    mode: str
    num_queries: int
    wall_s: float
    qps: float
    latencies_ms: List[float] = field(default_factory=list)

    def percentile(self, p: float) -> float:
        if not self.latencies_ms:
            return 0.0
        s = sorted(self.latencies_ms)
        return s[min(int(len(s) * p / 100.0), len(s) - 1)]

    def to_json(self) -> dict:
        return {
            "mode": self.mode,
            "numQueries": self.num_queries,
            "wallSeconds": round(self.wall_s, 3),
            "qps": round(self.qps, 1),
            "avgMs": round(sum(self.latencies_ms) / max(len(self.latencies_ms), 1), 3),
            "p50Ms": round(self.percentile(50), 3),
            "p90Ms": round(self.percentile(90), 3),
            "p95Ms": round(self.percentile(95), 3),
            "p99Ms": round(self.percentile(99), 3),
        }


def http_query_fn(broker_url: str, timeout_s: float = 60.0) -> Callable[[str], None]:
    endpoint = broker_url.rstrip("/") + "/query"

    def run(pql: str) -> None:
        body = json.dumps({"pql": pql}).encode()
        req = urllib.request.Request(endpoint, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            r.read()

    return run


class QueryRunner:
    def __init__(self, query_fn: Callable[[str], None]) -> None:
        self.query_fn = query_fn

    def _timed(self, pql: str) -> float:
        t0 = time.perf_counter()
        self.query_fn(pql)
        return (time.perf_counter() - t0) * 1000.0

    def single_thread(self, queries: Sequence[str], rounds: int = 1) -> RunnerReport:
        lat: List[float] = []
        t0 = time.perf_counter()
        for _ in range(rounds):
            for q in queries:
                lat.append(self._timed(q))
        wall = time.perf_counter() - t0
        return RunnerReport("singleThread", len(lat), wall, len(lat) / wall, lat)

    def multi_threads(self, queries: Sequence[str], num_threads: int = 4, rounds: int = 1) -> RunnerReport:
        work = [q for _ in range(rounds) for q in queries]
        lat: List[float] = []
        lock = threading.Lock()

        def one(q: str) -> None:
            ms = self._timed(q)
            with lock:
                lat.append(ms)

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(max_workers=num_threads) as pool:
            list(pool.map(one, work))
        wall = time.perf_counter() - t0
        return RunnerReport("multiThreads", len(lat), wall, len(lat) / wall, lat)

    def target_qps(self, queries: Sequence[str], qps: float, duration_s: float = 10.0) -> RunnerReport:
        interval = 1.0 / qps
        lat: List[float] = []
        lock = threading.Lock()
        start = time.perf_counter()
        stop = start + duration_s
        futures = []
        i = 0
        with concurrent.futures.ThreadPoolExecutor(max_workers=32) as pool:
            next_t = time.perf_counter()
            while time.perf_counter() < stop:
                q = queries[i % len(queries)]
                i += 1

                def one(q=q):
                    ms = self._timed(q)
                    with lock:
                        lat.append(ms)

                futures.append(pool.submit(one))
                next_t += interval
                delay = next_t - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            concurrent.futures.wait(futures, timeout=60)
        # wall covers the DRAIN too: a backlogged system finishing its
        # queue after the submission window must not report the backlog
        # as achieved throughput (the r5 curve briefly showed 256 QPS
        # "achieved" at 470ms p50 on a ~70 QPS system this way)
        wall = max(time.perf_counter() - start, 1e-9)
        return RunnerReport("targetQPS", len(lat), wall, len(lat) / wall, lat)
