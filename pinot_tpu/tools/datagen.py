"""Seeded data generators for tests, quickstarts, and benchmarks.

Covers the role of the reference's ``pinot-tools`` data generator and the
TPC-H harness in ``contrib/pinot-benchmark`` (lineitem-shaped generator
below; real TPC-H data files aren't shipped, so the distribution is
synthetic but shape- and cardinality-faithful for Q0-Q6).
"""
from __future__ import annotations

import random
import string
from typing import Any, Dict, List, Optional, Sequence

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec

Row = Dict[str, Any]


def random_rows(
    schema: Schema,
    num_rows: int,
    seed: int = 0,
    cardinality: int = 20,
    mv_max: int = 3,
) -> List[Row]:
    """Random rows for a schema with bounded per-column cardinality."""
    rng = random.Random(seed)
    # Fixed value pools per column so cardinality is bounded.
    pools: Dict[str, List[Any]] = {}
    for spec in schema.all_fields():
        st = spec.stored_type
        if st == DataType.STRING:
            pools[spec.name] = [
                "".join(rng.choices(string.ascii_lowercase, k=rng.randint(3, 8)))
                for _ in range(cardinality)
            ]
        elif st in (DataType.INT, DataType.LONG):
            pools[spec.name] = [rng.randint(0, 10_000) for _ in range(cardinality)]
        else:
            pools[spec.name] = [round(rng.uniform(-100, 100), 3) for _ in range(cardinality)]

    rows: List[Row] = []
    for _ in range(num_rows):
        row: Row = {}
        for spec in schema.all_fields():
            pool = pools[spec.name]
            if spec.single_value:
                row[spec.name] = rng.choice(pool)
            else:
                row[spec.name] = [rng.choice(pool) for _ in range(rng.randint(1, mv_max))]
        rows.append(row)
    return rows


def make_test_schema(with_mv: bool = True) -> Schema:
    """A small mixed-type schema exercising every stored type."""
    dims = [
        FieldSpec("dimStr", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("dimInt", DataType.INT, FieldType.DIMENSION),
        FieldSpec("dimLong", DataType.LONG, FieldType.DIMENSION),
    ]
    if with_mv:
        dims.append(FieldSpec("dimStrMV", DataType.STRING_ARRAY, FieldType.DIMENSION, single_value=False))
        dims.append(FieldSpec("dimIntMV", DataType.INT_ARRAY, FieldType.DIMENSION, single_value=False))
    metrics = [
        FieldSpec("metInt", DataType.INT, FieldType.METRIC),
        FieldSpec("metFloat", DataType.FLOAT, FieldType.METRIC),
        FieldSpec("metDouble", DataType.DOUBLE, FieldType.METRIC),
    ]
    time_field = TimeFieldSpec("daysSinceEpoch", DataType.INT, time_unit="DAYS")
    return Schema("testTable", dimensions=dims, metrics=metrics, time_field=time_field)


# ---------------------------------------------------------------------------
# baseballStats-shaped quickstart data (Quickstart.java:33 /
# sample_data/baseball.schema — synthetic; shape- and type-faithful)
# ---------------------------------------------------------------------------

_TEAMS = ["BOS", "NYA", "CHA", "SFN", "LAN", "SLN", "ATL", "SEA", "OAK", "TEX"]
_LEAGUES = ["AL", "NL"]
_FIRST = ["hank", "babe", "ty", "willie", "ted", "lou", "joe", "mickey", "stan", "cal"]
_LAST = ["aaron", "ruth", "cobb", "mays", "williams", "gehrig", "dimaggio", "mantle", "musial", "ripken"]


def baseball_schema() -> Schema:
    return Schema(
        "baseballStats",
        dimensions=[
            FieldSpec("playerName", DataType.STRING),
            FieldSpec("teamID", DataType.STRING),
            FieldSpec("league", DataType.STRING),
            FieldSpec("yearID", DataType.INT),
        ],
        metrics=[
            FieldSpec("runs", DataType.INT, FieldType.METRIC),
            FieldSpec("hits", DataType.INT, FieldType.METRIC),
            FieldSpec("homeRuns", DataType.INT, FieldType.METRIC),
            FieldSpec("atBats", DataType.INT, FieldType.METRIC),
        ],
    )


def baseball_rows(num_rows: int = 10_000, seed: int = 42) -> List[Row]:
    rng = random.Random(seed)
    players = [f"{f} {l}" for f in _FIRST for l in _LAST]
    rows: List[Row] = []
    for _ in range(num_rows):
        at_bats = rng.randint(50, 650)
        hits = rng.randint(0, at_bats // 2)
        rows.append(
            {
                "playerName": rng.choice(players),
                "teamID": rng.choice(_TEAMS),
                "league": rng.choice(_LEAGUES),
                "yearID": rng.randint(1980, 2015),
                "runs": rng.randint(0, 140),
                "hits": hits,
                "homeRuns": rng.randint(0, 60),
                "atBats": at_bats,
            }
        )
    return rows


# ---------------------------------------------------------------------------
# TPC-H lineitem-shaped generator (contrib/pinot-benchmark workload shape)
# ---------------------------------------------------------------------------

_SHIP_MODES = ["RAIL", "FOB", "MAIL", "SHIP", "TRUCK", "AIR", "REG AIR"]
_RETURN_FLAGS = ["R", "A", "N"]
_LINE_STATUS = ["O", "F"]


def lineitem_schema() -> Schema:
    return Schema(
        "lineitem",
        dimensions=[
            FieldSpec("l_returnflag", DataType.STRING),
            FieldSpec("l_linestatus", DataType.STRING),
            FieldSpec("l_shipmode", DataType.STRING),
            FieldSpec("l_shipdate", DataType.STRING),
            FieldSpec("l_receiptdate", DataType.STRING),
        ],
        metrics=[
            FieldSpec("l_quantity", DataType.DOUBLE, FieldType.METRIC),
            FieldSpec("l_extendedprice", DataType.DOUBLE, FieldType.METRIC),
            FieldSpec("l_discount", DataType.DOUBLE, FieldType.METRIC),
            FieldSpec("l_tax", DataType.DOUBLE, FieldType.METRIC),
        ],
    )


def _rand_date(rng: random.Random, lo_year: int = 1992, hi_year: int = 1998) -> str:
    y = rng.randint(lo_year, hi_year)
    m = rng.randint(1, 12)
    d = rng.randint(1, 28)
    return f"{y:04d}-{m:02d}-{d:02d}"


def _synthetic_columnar_segment(
    schema: Schema,
    table_name: str,
    dict_values: Dict[str, Any],
    num_rows: int,
    seed: int,
    name: str,
    clustered_column: Optional[str] = None,
    time_column: Optional[str] = None,
    rng=None,
):
    """Shared fast-path builder behind every synthetic_*_segment:
    ColumnData built directly from per-column value pools (dictIds drawn
    uniformly) instead of the two-pass row builder, so 10M+ row segments
    construct in seconds.  ``clustered_column`` is sorted after draw
    (arrival-ordered data: zone maps / docrange fast paths have
    something to prune, as a sorted Pinot column does).  Callers whose
    value pools consumed random state pass their ``rng`` so the draw
    sequence (and thus seeded data) stays reproducible."""
    import numpy as np

    from pinot_tpu.common.schema import DataType
    from pinot_tpu.segment.dictionary import Dictionary
    from pinot_tpu.segment.immutable import (
        ColumnData,
        ColumnMetadata,
        ImmutableSegment,
        SegmentMetadata,
    )

    rng = rng if rng is not None else np.random.default_rng(seed)
    columns = {}
    for spec in schema.all_fields():
        vals = dict_values[spec.name]
        if spec.stored_type == DataType.STRING:
            d = Dictionary(DataType.STRING, sorted(set(vals)))
        else:
            d = Dictionary(spec.stored_type, np.unique(np.asarray(vals)))
        card = d.cardinality
        fwd = rng.integers(0, card, size=num_rows, dtype=np.int64).astype(np.int32)
        if spec.name == clustered_column:
            fwd.sort()
        columns[spec.name] = ColumnData(
            metadata=ColumnMetadata(
                name=spec.name,
                data_type=spec.data_type,
                field_type=spec.field_type,
                single_value=True,
                cardinality=card,
                total_docs=num_rows,
                # true sortedness: a clustered column qualifies for the
                # docrange fast path (plan.py), as a sorted Pinot column
                # does for SortedInvertedIndexBasedFilterOperator
                is_sorted=bool(num_rows == 0 or np.all(fwd[1:] >= fwd[:-1])),
                total_number_of_entries=num_rows,
                min_value=d.min_value,
                max_value=d.max_value,
            ),
            dictionary=d,
            fwd=fwd,
        )
    smeta = SegmentMetadata(
        segment_name=name,
        table_name=table_name,
        num_docs=num_rows,
        columns={c.metadata.name: c.metadata for c in columns.values()},
        time_column=time_column,
    )
    seg = ImmutableSegment(metadata=smeta, columns=columns)
    smeta.crc = hash((name, num_rows, seed)) & 0xFFFFFFFF  # cheap identity
    return seg


def synthetic_lineitem_segment(num_rows: int, seed: int = 7, name: str = "li0"):
    """Fast numpy-path lineitem segment for benchmarks (see
    ``_synthetic_columnar_segment``)."""
    import numpy as np

    rng = np.random.default_rng(seed)

    def dates(n: int) -> List[str]:
        out = []
        for y in range(1992, 1999):
            for m in range(1, 13):
                for d in range(1, 29):
                    out.append(f"{y:04d}-{m:02d}-{d:02d}")
                    if len(out) >= n:
                        return sorted(out)
        return sorted(out)

    dict_values = {
        "l_returnflag": sorted(_RETURN_FLAGS),
        "l_linestatus": sorted(_LINE_STATUS),
        "l_shipmode": sorted(_SHIP_MODES),
        "l_shipdate": dates(2000),
        "l_receiptdate": dates(2000),
        "l_quantity": np.arange(1.0, 51.0),
        "l_extendedprice": np.round(np.sort(rng.uniform(900.0, 105_000.0, 16384)), 2),
        "l_discount": np.round(np.arange(0.0, 0.11, 0.01), 2),
        "l_tax": np.round(np.arange(0.0, 0.09, 0.01), 2),
    }
    return _synthetic_columnar_segment(
        lineitem_schema(), "lineitem", dict_values, num_rows, seed, name,
        clustered_column="l_shipdate", rng=rng,
    )


def lineitem_rows(num_rows: int, seed: int = 7) -> List[Row]:
    rng = random.Random(seed)
    rows: List[Row] = []
    for _ in range(num_rows):
        rows.append(
            {
                "l_returnflag": rng.choice(_RETURN_FLAGS),
                "l_linestatus": rng.choice(_LINE_STATUS),
                "l_shipmode": rng.choice(_SHIP_MODES),
                "l_shipdate": _rand_date(rng),
                "l_receiptdate": _rand_date(rng),
                "l_quantity": float(rng.randint(1, 50)),
                "l_extendedprice": round(rng.uniform(900.0, 105_000.0), 2),
                "l_discount": round(rng.uniform(0.0, 0.1), 2),
                "l_tax": round(rng.uniform(0.0, 0.08), 2),
            }
        )
    return rows


# ---------------------------------------------------------------------------
# Synthetic ad-events (the BASELINE.json north-star config: "Synthetic
# ad-events 1B rows: high-cardinality distinctCountHLL group-by")
# ---------------------------------------------------------------------------

ADEVENTS_TABLE = "adevents"


def adevents_schema() -> Schema:
    return Schema(
        ADEVENTS_TABLE,
        dimensions=[
            FieldSpec("campaign_id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("site_id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("user_id", DataType.LONG, FieldType.DIMENSION),
        ],
        metrics=[FieldSpec("clicks", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("event_time", DataType.LONG, time_unit="MILLISECONDS"),
    )


def synthetic_adevents_segment(
    num_rows: int,
    seed: int = 7,
    name: str = "ad0",
    campaign_card: int = 1024,
    site_card: int = 128,
    user_card: int = 1 << 20,
    user_universe: int = 1 << 26,
):
    """Fast numpy-path ad-events segment: the high-cardinality HLL
    workload.  ``user_id`` draws ``user_card`` distinct users per
    segment from a ``user_universe``-wide population, so segments
    overlap partially (the realistic dedup case) and the GLOBAL
    dictionary grows toward the universe size across segments."""
    import numpy as np

    rng = np.random.default_rng(seed)
    users = np.unique(
        rng.integers(0, user_universe, size=int(user_card * 1.05), dtype=np.int64)
    )
    t0 = 1_700_000_000_000 + seed * 3_600_000
    dict_values = {
        "campaign_id": np.arange(campaign_card, dtype=np.int64),
        "site_id": np.arange(site_card, dtype=np.int64),
        "user_id": users,
        "clicks": np.arange(16, dtype=np.int64),
        # clustered: events arrive in time order (zone-map fodder)
        "event_time": t0 + np.arange(4096, dtype=np.int64) * 1000,
    }
    return _synthetic_columnar_segment(
        adevents_schema(), ADEVENTS_TABLE, dict_values, num_rows, seed, name,
        clustered_column="event_time", time_column="event_time", rng=rng,
    )


def tile_segments(distinct_segments, total: int):
    """Replicate ``distinct_segments`` round-robin up to ``total``
    segments under fresh names.  The clones SHARE the originals' numpy
    arrays (host RAM stays O(distinct)), but stage and execute as
    independent segments — the standard trick for benchmarking at row
    counts datagen can't build in reasonable time.  Results are those
    of the tiled data (e.g. distinct counts don't grow past the
    distinct set); throughput numbers are unaffected, which is what
    the tiling is for."""
    from pinot_tpu.segment.immutable import ImmutableSegment, SegmentMetadata

    out = []
    for i in range(total):
        base = distinct_segments[i % len(distinct_segments)]
        if i < len(distinct_segments):
            out.append(base)
            continue
        m = base.metadata
        smeta = SegmentMetadata(
            segment_name=f"{m.segment_name}_t{i}",
            table_name=m.table_name,
            num_docs=m.num_docs,
            columns=dict(m.columns),
            time_column=m.time_column,
        )
        smeta.crc = hash((smeta.segment_name, m.num_docs)) & 0xFFFFFFFF
        out.append(ImmutableSegment(metadata=smeta, columns=base.columns))
    return out


def synthetic_baseball_segment(num_rows: int, seed: int = 7, name: str = "bb0"):
    """Fast numpy-path baseballStats segment (quickstart config at bench
    scale): same schema/cardinalities as ``baseball_rows``, built
    columnar so 10M+ row segments construct in seconds."""
    import numpy as np

    dict_values = {
        "playerName": sorted(f"{f} {l}" for f in _FIRST for l in _LAST),
        "teamID": sorted(_TEAMS),
        "league": sorted(_LEAGUES),
        "yearID": np.arange(1980, 2016, dtype=np.int64),
        "runs": np.arange(0, 141, dtype=np.int64),
        "hits": np.arange(0, 326, dtype=np.int64),
        "homeRuns": np.arange(0, 61, dtype=np.int64),
        "atBats": np.arange(50, 651, dtype=np.int64),
    }
    return _synthetic_columnar_segment(
        baseball_schema(), "baseballStats", dict_values, num_rows, seed, name
    )
