"""Pretty-print an EXPLAIN / EXPLAIN ANALYZE plan tree as ASCII.

Input is the ``explain`` object a broker returns for an EXPLAIN query
(``BrokerResponse.to_json()["explain"]`` — see ``engine/explain.py``
for the node schema), either from a saved response JSON / bare explain
JSON on disk or stdin, or fetched live with ``--broker ... --pql``
(the EXPLAIN prefix is added automatically unless already present;
``--analyze`` upgrades it to EXPLAIN ANALYZE).

Usage:
  python -m pinot_tpu.tools.explain_dump response.json
  python -m pinot_tpu.tools.explain_dump --broker http://127.0.0.1:8099 \\
      --pql "SELECT count(*) FROM myTable" [--analyze]

EXPLAIN ANALYZE renders estimated-vs-actual side by side with the
delta highlighted (``!`` marks a >2x miss) — the estimate-quality
feedback loop for the plan-stats registry.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _fmt_cost(cost: Dict[str, Any]) -> str:
    # nested dicts (perQuery, roofline) render on their own lines
    return "  ".join(
        f"{k}={round(v, 3) if isinstance(v, float) else v}"
        for k, v in sorted(cost.items())
        if not isinstance(v, dict)
    )


def _fmt_qty(v: float) -> str:
    """1.23e9 -> '1.23G' (flops / bytes-scale quantities)."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return str(v)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.0f}"


def render_cost_analysis(dev: Dict[str, Any]) -> str:
    """The compile/cost-analysis block of a device plan node: static
    flops / bytes-accessed estimates when the analysis landed, the
    explicit 'unavailable'/'pending' states otherwise."""
    comp = dev.get("compile") or {}
    ca = comp.get("costAnalysis")
    if isinstance(ca, dict):
        parts = []
        if "flops" in ca:
            parts.append(f"est flops={_fmt_qty(ca['flops'])}")
        if "bytesAccessed" in ca:
            parts.append(f"est bytes={_fmt_qty(ca['bytesAccessed'])}")
        if "peakMemoryBytes" in ca:
            parts.append(f"peak mem={_fmt_qty(ca['peakMemoryBytes'])}")
        src = ca.get("source")
        return (
            "  cost-analysis: " + "  ".join(parts)
            + (f"  ({src})" if src else "") + "\n"
        )
    if ca in ("unavailable", "pending"):
        return f"  cost-analysis: {ca}\n"
    return ""


def render_roofline(est: Dict[str, Any], indent: str = "  ") -> str:
    """Achieved-utilization footer for a shape that has executed on
    device (the plan-stats roofline riding EXPLAIN's history
    estimate): measured achieved bytes/s + FLOP/s against the declared
    platform peaks."""
    roof = (est or {}).get("roofline")
    if not isinstance(roof, dict):
        return ""
    parts = [f"achieved={_fmt_qty(roof.get('achievedBytesPerSec', 0))}B/s"]
    if roof.get("achievedFlopsPerSec"):
        parts.append(f"{_fmt_qty(roof['achievedFlopsPerSec'])}FLOP/s")
    frac = roof.get("rooflineFraction")
    parts.append(
        "roofline=n/a (no peak declared)"
        if frac is None
        else f"roofline={float(frac) * 100.0:.2f}%"
    )
    return indent + "utilization: " + "  ".join(parts) + "\n"


def _delta_line(est: float, act: float, label: str) -> str:
    """estimated vs actual with the ratio highlighted."""
    if est <= 0 and act <= 0:
        return ""
    ratio = act / est if est > 0 else float("inf")
    flag = " !" if (ratio > 2.0 or ratio < 0.5) else ""
    shown = f"{ratio:.2f}x" if est > 0 else "n/a"
    return f"    {label}: est={int(est)}  actual={int(act)}  ({shown}){flag}\n"


def render_join_node(join: Dict[str, Any]) -> str:
    """Join-plan block (broker/joinplan.py node): the chosen strategy,
    the colocation verdict, per-side estimates vs actuals, and the
    heavy-hitter split decision.  Pure; unit-testable."""
    lines: List[str] = []
    forced = "  (forced)" if join.get("forced") else ""
    lines.append(f"join: {join.get('strategy')}{forced}  on {join.get('on')}")
    colo = join.get("colocated") or {}
    lines.append(
        f"  colocated: {'eligible' if colo.get('eligible') else 'ineligible'}"
        f" — {colo.get('reason', '')}"
    )
    build = join.get("build") or {}
    if build:
        est_rows = build.get("estRows")
        est_b = build.get("estBytes")
        lines.append(
            f"  build side {build.get('table')}: est "
            f"{est_rows if est_rows is not None else '?'} rows / "
            f"{_fmt_qty(est_b) if est_b is not None else '?'}B "
            f"(source={build.get('estSource') or 'none'})"
        )
    budget = join.get("budget") or {}
    if budget:
        lines.append(
            f"  broadcast budget: {budget.get('broadcastRows')} rows / "
            f"{_fmt_qty(budget.get('broadcastBytes', 0))}B"
        )
    skew = join.get("skew") or {}
    if skew:
        lines.append(
            f"  skew: split={'on' if skew.get('splitEnabled') else 'OFF'}  "
            f"heavyFactor={skew.get('heavyFactor')}"
        )
    actual = join.get("actual") or {}
    if actual:
        parts = [f"strategy={actual.get('strategy')}"]
        for k in ("buildRows", "probeRows", "broadcastBytes", "shuffleBytes",
                  "heavyHitterSplits", "owners"):
            if actual.get(k) is not None:
                parts.append(f"{k}={actual[k]}")
        lines.append("  actual: " + "  ".join(parts))
        per = actual.get("shuffleBytesPerServer") or {}
        if per:
            mean = sum(per.values()) / max(1, len(per))
            worst = max(per.values()) / mean if mean else 0.0
            lines.append(
                "  shuffle bytes/server: "
                + "  ".join(f"{s}={_fmt_qty(v)}B" for s, v in sorted(per.items()))
                + f"  (max/mean={worst:.2f}x)"
            )
    return "\n".join(lines) + "\n"


def render_explain(obj: Dict[str, Any]) -> str:
    """Full response JSON or bare explain object -> ASCII tree.  Pure;
    unit-testable."""
    explain = obj.get("explain") if isinstance(obj, dict) and "explain" in obj else obj
    if not isinstance(explain, dict) or "servers" not in explain:
        return "(no explain tree in input — was the query EXPLAIN-prefixed?)\n"
    mode = explain.get("mode", "plan")
    lines: List[str] = []
    lines.append(
        f"EXPLAIN{' ANALYZE' if mode == 'analyze' else ''}  "
        f"digest={explain.get('planDigest')}  {explain.get('summary', '')}"
    )
    tiers = explain.get("tierCounts") or {}
    if tiers:
        lines.append(
            "tiers: "
            + "  ".join(f"{k}={v}" for k, v in sorted(tiers.items()))
        )
    est = explain.get("estimatedCost") or {}
    if est:
        lines.append(f"estimated: {_fmt_cost(est)}")
    join = explain.get("join")
    if join:
        lines.extend(render_join_node(join).rstrip("\n").split("\n"))
    out = "\n".join(lines) + "\n"

    for node in explain.get("servers") or []:
        out += (
            f"server {node.get('server')}  table={node.get('table')}  "
            f"segments={node.get('numSegments')}  docs={node.get('totalDocs')}\n"
        )
        dev = node.get("device")
        if dev:
            comp = dev.get("compile") or {}
            comp_str = comp.get("state", "?")
            if comp.get("firstCallMs") is not None:
                comp_str += f" (firstCallMs={comp['firstCallMs']})"
            quarantined = "  QUARANTINED" if dev.get("quarantined") else ""
            out += (
                f"  device plan {dev.get('planDigest')}  "
                f"compile={comp_str}{quarantined}\n"
            )
            mesh = dev.get("mesh")
            if mesh:
                # mesh execution decision (engine/mesh.py): which lane
                # serves the shape and what the merge lowers to
                coll = mesh.get("collective")
                out += (
                    f"  mesh {mesh.get('shape')}  lane={mesh.get('laneIndex')}"
                    f"/{mesh.get('lanes')}  "
                    + (
                        f"shard={mesh.get('shardAxis')}  "
                        f"collective={','.join(coll)}\n"
                        if mesh.get("shardAxis")
                        else "single-chip (no sharding)\n"
                    )
                )
            out += render_cost_analysis(dev)
        staged = node.get("staged") or {}
        if staged.get("hbmBytes"):
            out += (
                f"  staged: {staged['hbmBytes']} bytes in HBM "
                f"({len(staged.get('columns') or [])} columns)\n"
            )
        by_tier: Dict[str, List[Dict[str, Any]]] = {}
        for seg in node.get("segments") or []:
            by_tier.setdefault(seg.get("tier", "?"), []).append(seg)
        for tier, segs in sorted(by_tier.items()):
            out += f"  {tier} x{len(segs)}: {segs[0].get('reason', '')}\n"
            for seg in segs:
                extra = ""
                if "candidateFraction" in seg:
                    extra = f"  candidateFraction={seg['candidateFraction']}"
                if "drivingColumn" in seg and seg["drivingColumn"]:
                    extra += f"  drivingColumn={seg['drivingColumn']}"
                out += f"    - {seg.get('segment')}{extra}\n"
        node_est = node.get("estimatedCost") or {}
        if mode == "analyze":
            actual = node.get("actualCost") or {}
            out += f"  actual: {_fmt_cost(actual)}\n"
            est_bytes = float(
                node_est.get("bytesScanned")
                or (node_est.get("perQuery") or {}).get("bytesScanned", 0)
                or 0
            )
            out += _delta_line(
                est_bytes, float(actual.get("bytesScanned", 0)), "bytesScanned"
            )
            out += render_roofline(node_est)
        elif node_est:
            out += f"  estimated: {_fmt_cost(node_est)}\n"
            out += render_roofline(node_est)

    if mode == "analyze":
        actual = explain.get("actualCost") or {}
        if actual:
            out += f"actual (merged): {_fmt_cost(actual)}\n"
        est_bytes = float((explain.get("estimatedCost") or {}).get("bytesScanned", 0))
        out += _delta_line(
            est_bytes, float(actual.get("bytesScanned", 0)), "bytesScanned (total)"
        )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pinot_tpu-explain-dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("file", nargs="?", help="broker response / explain JSON (default stdin)")
    p.add_argument("--broker", help="broker base URL: run --pql live")
    p.add_argument("--pql", help="query to explain against --broker")
    p.add_argument(
        "--analyze", action="store_true",
        help="use EXPLAIN ANALYZE (executes the query)",
    )
    args = p.parse_args(argv)
    if bool(args.broker) != bool(args.pql):
        p.error("--broker and --pql must be given together")

    if args.broker and args.pql:
        import urllib.request

        pql = args.pql.strip()
        if not pql.upper().startswith("EXPLAIN"):
            pql = ("EXPLAIN ANALYZE " if args.analyze else "EXPLAIN ") + pql
        req = urllib.request.Request(
            args.broker.rstrip("/") + "/query",
            data=json.dumps({"pql": pql}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            obj = json.loads(r.read())
    elif args.file:
        with open(args.file) as f:
            obj = json.load(f)
    else:
        obj = json.load(sys.stdin)

    text = render_explain(obj)
    sys.stdout.write(text)
    return 1 if text.startswith("(no explain tree") else 0


if __name__ == "__main__":
    raise SystemExit(main())
