"""Metric-name lint: every name used in the codebase must be cataloged.

The per-role catalogs in ``utils/metrics.py`` (``BROKER_METRIC_CATALOG``
etc.) are the single source of truth for series names.  This lint scans
the ``pinot_tpu`` package source for ``.meter("...")`` / ``.timer(...)``
/ ``.gauge(...)`` call sites and fails on any name that does not match
a catalog entry — so a typo'd metric name cannot silently fork a new
series that dashboards and alerts never see.

Dynamic names are declared in the catalogs with ``*`` wildcards
(``phase.*``, ``*.segmentCount``); an f-string call site is normalized
by replacing each ``{...}`` part with ``*`` before matching.

Run standalone (``python -m pinot_tpu.tools.metrics_lint``) or as the
tier-1 test ``tests/test_observability.py::test_metrics_lint``.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

# .meter("name") / .timer(f"phase.{x}") / .gauge('...') call sites
_CALL_RE = re.compile(
    r"""\.(?:meter|timer|gauge)\(\s*(f?)(['"])((?:(?!\2).)+)\2""",
)
# {expr} parts of an f-string (no nested-brace support needed here)
_FSTRING_EXPR_RE = re.compile(r"\{[^{}]*\}")


def _normalize(fprefix: str, name: str) -> str:
    """Call-site literal -> match pattern ('phase.{n}' -> 'phase.*')."""
    if fprefix:
        return _FSTRING_EXPR_RE.sub("*", name)
    return name


_CANON_RE = re.compile(r"\*+")


def _matches(used: str, entry: str) -> bool:
    """A literal use matches a literal entry exactly or a wildcard entry
    as a glob; an f-string use (normalized to ``*``) matches an entry
    with the same fixed skeleton, or any literal entry the pattern
    covers (``heal.*`` is satisfied by ``heal.deviceFailures``)."""
    import fnmatch

    if "*" in used:
        if _CANON_RE.sub("*", used) == _CANON_RE.sub("*", entry):
            return True
        return "*" not in entry and fnmatch.fnmatchcase(entry, used)
    if "*" in entry:
        return fnmatch.fnmatchcase(used, entry)
    return used == entry


def collect_usages(package_dir: str) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, normalized name)] for every metric call site
    in the package source (tests and tools/ probes are out of scope —
    they may use throwaway registries)."""
    out: List[Tuple[str, int, str]] = []
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_dir)
            if rel == os.path.join("tools", "metrics_lint.py"):
                continue  # this file's docstring/regex would self-match
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    for m in _CALL_RE.finditer(line):
                        out.append((rel, lineno, _normalize(m.group(1), m.group(3))))
    return out


def run_lint(package_dir: str = None) -> List[str]:
    """Returns a list of problem strings; empty means clean."""
    from pinot_tpu.utils import metrics as metrics_mod

    if package_dir is None:
        import pinot_tpu

        package_dir = os.path.dirname(os.path.abspath(pinot_tpu.__file__))
    catalog: Dict[str, str] = {}
    for role_catalog in metrics_mod.METRIC_CATALOGS.values():
        catalog.update(role_catalog)
    problems: List[str] = []
    for rel, lineno, name in collect_usages(package_dir):
        if not any(_matches(name, entry) for entry in catalog):
            problems.append(
                f"{rel}:{lineno}: metric name {name!r} is not in any "
                f"per-role catalog (utils/metrics.py) — add it there or "
                f"fix the typo"
            )
    return problems


def main(argv=None) -> int:
    problems = run_lint()
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"metrics lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("metrics lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
