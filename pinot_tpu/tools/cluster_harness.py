"""In-process cluster harness: controller + N servers + broker in one
process.

The reference's ``PerfBenchmarkDriver.java:61`` (starts the whole
cluster in-process, :160-162) and the integration tests' ``ClusterTest``
use the same trick; this is the standard harness for quickstarts, perf
runs, and integration tests.

``--scenario kill-server|drain|rolling-restart`` runs the cluster
self-stabilization chaos scenarios (closed-loop query load while a
server dies / drains / every server rolls): the SAME scenario code
drives manual chaos runs from this CLI and the deterministic tier-1
chaos tests (``tests/test_stabilizer.py``).

``--scenario partition-server|partition-controller|asymmetric-partition
|split-brain`` runs the network-partition chaos scenarios (ISSUE 9)
over a ``NetworkedCluster`` — controller + servers + broker as real
HTTP/TCP endpoints in one process, every link routed through a shared
``NetworkFaultInjector`` — proving lease-fenced serving and the
epoch-fenced commit plane under severed links (tier-1 twins in
``tests/test_partition.py``).

``--scenario rolling-restart-warm`` is the warm-start acceptance
(ISSUE 16): every server is replaced by a FRESH instance sharing only
the persistent compile cache while the steady workload replays — zero
failed queries, ``compile.cold == 0`` on restarted servers (persistent
ledger + fleet prewarming), and readiness-gated movement (trims wait
for warming destinations; the event ring proves it).  Tier-1 twin in
``tests/test_warmstart.py``.

``--scenario hbm-pressure`` runs the tiered-residency chaos acceptance
(ISSUE 18): addressable staged data ~8x the HBM cap under a hot
closed loop + cold-table sweep — zero failed queries, hot-set p99
bounded against its uncapped baseline, demotion/promotion/cold-load
counters proving HBM <-> host <-> disk cycled, and an injected
allocation failure healed by demotion (tier-1 twin in
``tests/test_chaos_hbm_pressure.py``).

``--scenario audit-divergence`` runs the correctness-audit chaos
acceptance (ISSUE 19): a seeded fault injector silently corrupts one
serving tier's aggregates under closed-loop load — the shadow
differential auditor must detect the divergence within budget,
quarantine the (plan digest, tier), and every answer after the
quarantine must be byte-identical to the pre-corruption reference
with zero failed queries (tier-1 twin in ``tests/test_audit.py``).

``--scenario elastic-fleet`` runs the fleet-breadth chaos acceptance
(ISSUE 15): 100+ tables under mixed ingest+query closed-loop load,
a forced hot-tenant skew, a live make-before-break rebalance, and a
mid-rebalance controller restart — zero failed queries, zero
lost/duplicate rows, exactly one committed copy per sequence (tier-1
twin in ``tests/test_elastic_fleet.py``).
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.broker.starter import BrokerStarter
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.starter import ServerStarter
from pinot_tpu.transport.local import LocalTransport


class InProcessCluster:
    def __init__(
        self,
        num_servers: int = 2,
        data_dir: Optional[str] = None,
        mesh=None,
        http: bool = False,
        timeout_ms: float = 15_000.0,
        max_pending: int = 64,
    ) -> None:
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_cluster_")
        self.controller = Controller(self.data_dir)
        self.transport = LocalTransport()

        self.servers: List[ServerInstance] = []
        self.server_starters: List[ServerStarter] = []
        addresses: Dict[str, tuple] = {}
        for i in range(num_servers):
            server = ServerInstance(f"server{i}", mesh=mesh, max_pending=max_pending)
            starter = ServerStarter(server, self.controller.resources)
            starter.start()
            address = (server.name, 0)
            self.transport.register(address, server.handle_request)
            addresses[server.name] = address
            self.servers.append(server)
            self.server_starters.append(starter)

        self.broker = BrokerRequestHandler(
            self.transport, addresses, name="broker0", timeout_ms=timeout_ms
        )
        self.http: Optional[BrokerHttpServer] = None
        broker_url = None
        if http:
            self.http = BrokerHttpServer(self.broker)
            self.http.start()
            broker_url = f"http://{self.http.host}:{self.http.port}"
        self.broker_starter = BrokerStarter(
            self.broker, self.controller.resources, url=broker_url
        )
        self.broker_starter.start()

    def add_server(self, name: Optional[str] = None, mesh=None) -> ServerInstance:
        """Join a new server into the running cluster (elastic scale-out;
        pair with controller.rebalance_table to move segments onto it)."""
        name = name or f"server{len(self.servers)}"
        server = ServerInstance(name, mesh=mesh)
        starter = ServerStarter(server, self.controller.resources)
        starter.start()
        address = (server.name, 0)
        self.transport.register(address, server.handle_request)
        self.broker.set_server_address(server.name, address)
        self.servers.append(server)
        self.server_starters.append(starter)
        return server

    # -- convenience API ---------------------------------------------
    def add_offline_table(
        self, schema: Schema, table_name: Optional[str] = None, **config_kwargs
    ) -> str:
        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name, table_type="OFFLINE", **config_kwargs
        )
        return self.controller.add_table(config)

    def add_realtime_table(
        self,
        schema: Schema,
        stream,
        table_name: Optional[str] = None,
        rows_per_segment: int = 1000,
        replication: int = 1,
    ) -> str:
        from pinot_tpu.common.tableconfig import StreamConfig

        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name,
            table_type="REALTIME",
            replication=replication,
            stream=StreamConfig(stream_type="memory", rows_per_segment=rows_per_segment),
        )
        return self.controller.add_realtime_table(config, stream)

    def upload(self, physical_table: str, segment: ImmutableSegment) -> None:
        self.controller.upload_segment(physical_table, segment)

    def query(self, pql: str, trace: bool = False) -> BrokerResponse:
        return self.broker.handle_pql(pql, trace=trace)

    def stop(self) -> None:
        if self.http is not None:
            self.http.stop()
        # history recorders are per-role daemon threads; stop them with
        # the cluster so tests don't accumulate tick loops (schedulers/
        # lanes are left as-is — stop() must not fail in-flight queries)
        self.broker.shutdown()
        for s in self.servers:
            s.history.stop()
            s.auditor.stop()
        self.controller.stop()


def single_server_broker(
    table: str,
    segments,
    timeout_ms: float = 600_000.0,
    max_pending: int = 64,
    **server_kwargs,
):
    """One in-process server + broker over LocalTransport — the
    minimal serving topology every bench uses (bench.py,
    tools/config_bench.py).  The generous default timeout covers the
    first query's staging + compile on a tunneled chip.  Extra kwargs
    reach the ServerInstance (e.g. ``pipeline=False`` for the serial
    executor path); the instance is reachable as
    ``broker.local_servers[0]`` so benches can read lane/scheduler
    counters."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.routing import RoutingTableProvider

    server = ServerInstance("benchServer", max_pending=max_pending, **server_kwargs)
    for seg in segments:
        server.add_segment(table, seg)
    transport = LocalTransport()
    transport.register(("benchServer", 0), server.handle_request)
    routing = RoutingTableProvider()
    routing.update(table, {s.segment_name: {"benchServer": "ONLINE"} for s in segments})
    broker = BrokerRequestHandler(
        transport,
        {"benchServer": ("benchServer", 0)},
        routing=routing,
        timeout_ms=timeout_ms,
    )
    broker.local_servers = [server]
    return broker


# ---------------------------------------------------------------------------
# Self-stabilization chaos scenarios (shared by the CLI and the tier-1
# chaos tests): closed-loop load over an in-process cluster while a
# server is killed / drained / the whole fleet rolling-restarts, with
# the SelfStabilizer driven explicitly (run_once — deterministic, no
# background sleeps).
# ---------------------------------------------------------------------------


class ClosedLoopLoad:
    """N client threads issuing the same query back-to-back, classifying
    every response: ok (complete + correct), partial (transient
    ``partialResponse`` — allowed during healing), failed (wrong count
    or exceptions on a response claiming to be complete).  Per-query
    latencies are recorded so overload scenarios can compare a tenant's
    loaded percentiles against its unloaded baseline."""

    def __init__(
        self, cluster: "InProcessCluster", pql: str, expected_docs: Optional[int],
        clients: int = 3,
    ) -> None:
        self.cluster = cluster
        self.pql = pql
        # None = "any complete answer is ok" (live realtime tables,
        # where the expected count grows while ingest runs)
        self.expected_docs = expected_docs
        self.clients = clients
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self.total = 0
        self.ok = 0
        self.partials = 0
        self.failed = 0
        self.failures: List[str] = []  # first few failure descriptions
        self.latencies_ms: List[float] = []

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                resp = self.cluster.broker.handle_pql(self.pql)
            except Exception as e:  # a raised handler is always a failure
                with self._lock:
                    self.total += 1
                    self.failed += 1
                    if len(self.failures) < 8:
                        self.failures.append(f"{type(e).__name__}: {e}")
                continue
            ms = (time.perf_counter() - t0) * 1000.0
            with self._lock:
                self.total += 1
                self.latencies_ms.append(ms)
                if resp.partial_response:
                    self.partials += 1
                elif resp.exceptions or (
                    self.expected_docs is not None
                    and resp.num_docs_scanned != self.expected_docs
                ):
                    self.failed += 1
                    if len(self.failures) < 8:
                        self.failures.append(
                            f"docs={resp.num_docs_scanned}/{self.expected_docs} "
                            f"exceptions={[e.message for e in resp.exceptions][:2]}"
                        )
                else:
                    self.ok += 1

    def start(self) -> "ClosedLoopLoad":
        for i in range(self.clients):
            t = threading.Thread(target=self._loop, name=f"load-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    @staticmethod
    def _pct(sorted_ms: List[float], p: float) -> float:
        if not sorted_ms:
            return 0.0
        i = min(len(sorted_ms) - 1, int(round(p / 100.0 * (len(sorted_ms) - 1))))
        return sorted_ms[i]

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        lat = sorted(self.latencies_ms)
        return {
            "queries": self.total,
            "okQueries": self.ok,
            "partialQueries": self.partials,
            "failedQueries": self.failed,
            "failures": list(self.failures),
            "p50Ms": round(self._pct(lat, 50), 3),
            "p99Ms": round(self._pct(lat, 99), 3),
        }


class FloodLoad:
    """Open-throttle tenant: N threads hammering one table back-to-back,
    classifying every reply by SHED TIER — the noisy neighbor whose
    overflow must come back as typed 429/210, never as timeouts."""

    def __init__(self, cluster: "InProcessCluster", pql: str, clients: int = 4) -> None:
        self.cluster = cluster
        self.pql = pql
        self.clients = clients
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self.total = 0
        self.ok = 0
        self.shed_429 = 0  # broker admission (quota / concurrency / overload)
        self.shed_210 = 0  # server scheduler saturation (incl. 220 drain)
        self.timeouts = 0  # the failure mode overload protection must prevent
        self.other_failures = 0
        self.samples: List[str] = []

    def _classify(self, codes) -> str:
        from pinot_tpu.common.response import ErrorCode

        if ErrorCode.TOO_MANY_REQUESTS in codes:
            return "429"
        if (
            ErrorCode.SERVER_SCHEDULER_DOWN in codes
            or ErrorCode.SERVER_SHUTTING_DOWN in codes
        ):
            return "210"
        if (
            ErrorCode.EXECUTION_TIMEOUT in codes
            or ErrorCode.BROKER_TIMEOUT in codes
        ):
            return "timeout"
        return "other"

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                resp = self.cluster.broker.handle_pql(self.pql)
            except Exception as e:
                with self._lock:
                    self.total += 1
                    self.other_failures += 1
                    if len(self.samples) < 8:
                        self.samples.append(f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                self.total += 1
                if not resp.exceptions:
                    self.ok += 1
                    continue
                kind = self._classify({e.error_code for e in resp.exceptions})
                if kind == "429":
                    self.shed_429 += 1
                elif kind == "210":
                    self.shed_210 += 1
                elif kind == "timeout":
                    self.timeouts += 1
                else:
                    self.other_failures += 1
                    if len(self.samples) < 8:
                        self.samples.append(
                            f"codes={[e.error_code for e in resp.exceptions]} "
                            f"{resp.exceptions[0].message[:120]}"
                        )

    def start(self) -> "FloodLoad":
        for i in range(self.clients):
            t = threading.Thread(target=self._loop, name=f"flood-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        return {
            "queries": self.total,
            "okQueries": self.ok,
            "shed429": self.shed_429,
            "shed210": self.shed_210,
            "timeouts": self.timeouts,
            "otherFailures": self.other_failures,
            "samples": list(self.samples),
        }


def _build_scenario_cluster(
    num_servers: int, replication: int, num_segments: int,
    data_dir: Optional[str] = None, seed: int = 5,
):
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    cluster = InProcessCluster(num_servers=num_servers, data_dir=data_dir)
    # scenarios drive rounds explicitly; act on death immediately
    cluster.controller.stabilizer.grace_s = 0.0
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=replication)
    rows = random_rows(schema, 260, seed=seed)
    total = 0
    for i in range(num_segments):
        # skewed sizes: the stabilizer's doc-weighted placement is what
        # keeps re-replication balanced under this skew
        n = 30 + 45 * (i % 5)
        cluster.upload(physical, build_segment(schema, rows[:n], physical, f"seg{i}"))
        total += n
    return cluster, physical, total


def _replication_state(cluster, physical: str, excluded=()) -> Dict[str, Any]:
    res = cluster.controller.resources
    ideal = res.get_ideal_state(physical)
    sizes = sorted({len(r) for r in ideal.values()}) if ideal else []
    return {
        "segments": len(ideal),
        "replicaSetSizes": sizes,
        "onExcluded": sum(
            1 for r in ideal.values() if any(s in r for s in excluded)
        ),
        "viewConverged": res.get_external_view(physical) == ideal,
    }


def run_kill_server_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, rounds: int = 2, victim: str = "server0",
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Kill one server under closed-loop load: zero failed queries
    (replica failover absorbs the loss), full replication restored by
    the stabilizer within ``rounds`` rounds, dead replicas dropped."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.15)  # warm: some queries complete pre-fault
        # kill: data plane goes dark, then the control plane declares the
        # death (the heartbeat-expiry path calls the same liveness flip)
        cluster.transport.set_down((victim, 0))
        cluster.controller.resources.set_instance_alive(victim, False)
        for _ in range(rounds):
            cluster.controller.stabilizer.run_once()
        time.sleep(0.15)  # healed steady state under load
        summary = load.stop()
        state = _replication_state(cluster, physical, excluded=[victim])
        final = cluster.query("SELECT count(*) FROM testTable")
        want = min(replication, num_servers - 1)
        return {
            "scenario": "kill-server",
            "victim": victim,
            "rounds": rounds,
            **summary,
            **state,
            "replicationRestored": state["replicaSetSizes"] == [want]
            and state["onExcluded"] == 0,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
            "stabilizer": cluster.controller.stabilizer.metrics.snapshot()["meters"],
        }
    finally:
        cluster.stop()


def _drain_one(cluster, name: str, max_rounds: int = 6) -> int:
    """Drain ``name`` and run stabilizer rounds until its replicas are
    fully migrated; returns rounds used."""
    cluster.controller.drain_instance(name)
    used = 0
    while used < max_rounds:
        if cluster.controller.drain_status(name)["drained"]:
            break
        cluster.controller.stabilizer.run_once()
        used += 1
    return used


def run_drain_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, victim: str = "server0", data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Drain one server under load: new routing stops covering it, the
    stabilizer migrates every replica off, the drain endpoint reports
    drained, and no query fails along the way."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.15)
        rounds = _drain_one(cluster, victim)
        status = cluster.controller.drain_status(victim)
        time.sleep(0.15)
        summary = load.stop()
        state = _replication_state(cluster, physical, excluded=[victim])
        final = cluster.query("SELECT count(*) FROM testTable")
        return {
            "scenario": "drain",
            "victim": victim,
            "roundsToDrain": rounds,
            "drainStatus": {k: status[k] for k in ("draining", "remainingSegments", "drained")},
            **summary,
            **state,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
        }
    finally:
        cluster.stop()


def run_rolling_restart_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Rolling restart of EVERY server under load, one at a time:
    drain -> (replicas migrate) -> restart (down+dead, then back) ->
    undrain -> next.  Zero failed queries, zero permanent segment loss."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    res = cluster.controller.resources
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.1)
        rounds_per_server: Dict[str, int] = {}
        for server in [s.name for s in cluster.servers]:
            rounds_per_server[server] = _drain_one(cluster, server)
            assert cluster.controller.drain_status(server)["drained"], server
            # "restart": the process goes away (data plane down, death
            # declared) and comes back — it holds nothing, so this is
            # invisible to queries
            cluster.transport.set_down((server, 0))
            res.set_instance_alive(server, False)
            cluster.transport.set_down((server, 0), False)
            res.set_instance_alive(server, True)
            cluster.controller.undrain_instance(server)
            cluster.controller.stabilizer.run_once()
        time.sleep(0.1)
        summary = load.stop()
        state = _replication_state(cluster, physical)
        final = cluster.query("SELECT count(*) FROM testTable")
        return {
            "scenario": "rolling-restart",
            "roundsPerServer": rounds_per_server,
            **summary,
            **state,
            "noSegmentLoss": state["replicaSetSizes"] == [replication]
            and final.num_docs_scanned == total
            and not final.partial_response,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
        }
    finally:
        cluster.stop()


def _mirror_warming(cluster) -> None:
    """In-process stand-in for the networked heartbeat readiness feed:
    copy each live server's ``prewarm.warming`` flag into the
    controller's InstanceState (what the stabilizer's trim gate
    consults) and the broker's health tracker (what routing
    deprioritizes on).  The networked starter does exactly this on
    every heartbeat; scenarios that drive stabilizer rounds explicitly
    mirror explicitly."""
    res = cluster.controller.resources
    for s in cluster.servers:
        w = bool(s.prewarm.warming)
        res.set_instance_warming(s.name, w)
        cluster.broker.health.set_warming(s.name, w)


def run_rolling_restart_warm_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 1, data_dir: Optional[str] = None,
    cache_dir: Optional[str] = None,
    steady_s: float = 0.7,
    prewarm_timeout_s: float = 10.0,
    p99_multiple: float = 8.0, p99_floor_ms: float = 150.0,
    max_rounds: int = 120,
) -> Dict[str, Any]:
    """Rolling restart with WARM starts (ISSUE 16): every server is
    drained, killed, and replaced by a genuinely fresh process image
    (new ``ServerInstance`` — empty lane compile registries) sharing
    only the persistent compile cache, while a closed-loop workload
    replays the steady query mix.

    Proves the full warm-start story end to end:

    - ZERO failed queries across the whole roll;
    - ``compile.cold == 0`` on every restarted server — the steady
      phase recorded each plan digest in the persistent ledger, so the
      restarts' first launches classify ``persistentHit``/``prewarmed``,
      never cold;
    - the stabilizer's movement waits for warming destinations: drain
      drops and rebalance phase-2 trims defer while the receiving
      server prewarms (``rebalanceTrimDeferred`` in the event ring),
      and complete once it reports ready;
    - prewarming never enters a serving lane: the lane watchdog/stall
      counters on restarted servers stay zero;
    - roll-phase p99 stays bounded vs the steady baseline.

    ``clients=1`` by default: a sequential replay keeps the plan-shape
    set exactly equal to the steady phase's (no micro-batched combo
    shapes appearing for the first time mid-roll), which is what makes
    the ``compile.cold == 0`` bar deterministic.
    """
    from pinot_tpu.engine import compilecache

    cache_dir = cache_dir or tempfile.mkdtemp(prefix="pinot_tpu_warmcache_")
    prev_env = os.environ.get("PINOT_TPU_COMPILE_CACHE_DIR")
    os.environ["PINOT_TPU_COMPILE_CACHE_DIR"] = cache_dir
    compilecache.configure_jax_cache(cache_dir)
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    res = cluster.controller.resources
    stab = cluster.controller.stabilizer
    stab.prewarm_timeout_s = prewarm_timeout_s
    stab.rebalance_hysteresis = 1  # rounds are driven explicitly here
    restarted: List[str] = []
    try:
        # fleet workload feed: in-process, the broker's own plan-stat
        # registry IS the fleet roll-up the controller would serve
        def workload_source(tables, n):
            return cluster.broker.workload_snapshot(top=n, tables=tables)[
                "topByCount"
            ]

        for s in cluster.servers:
            s.prewarm.workload_source = workload_source
            s.prewarm.timeout_s = prewarm_timeout_s

        pql = "SELECT sum(metInt), count(*) FROM testTable GROUP BY dimStr TOP 5"
        count_pql = "SELECT count(*) FROM testTable"
        # warm BOTH shapes the scenario ever issues before measuring:
        # the steady baseline must not include the one-time cold, and
        # every digest a restarted server can see must be in the
        # persistent ledger before the first restart
        for warm_pql in (pql, count_pql):
            r = cluster.broker.handle_pql(warm_pql)
            assert not r.exceptions, r.exceptions
        # steady phase: populates the broker's workload registry (the
        # prewarm feed) AND the persistent plan ledger (via this run's
        # genuine colds) before any restart happens
        steady_load = ClosedLoopLoad(cluster, pql, total, clients).start()
        time.sleep(steady_s)
        steady = steady_load.stop()
        assert steady["failedQueries"] == 0, steady["failures"]

        roll_load = ClosedLoopLoad(cluster, pql, total, clients).start()
        rounds_per_server: Dict[str, int] = {}
        for i in range(len(cluster.servers)):
            old = cluster.servers[i]
            name = old.name
            # drain: replicas migrate off; each destination flips to
            # warming as the moved segments load, so dropping the
            # draining copy is readiness-gated (the deferral events
            # below prove the wait happened)
            cluster.controller.drain_instance(name)
            used = 0
            while used < max_rounds:
                _mirror_warming(cluster)
                stab.run_once()
                used += 1
                if cluster.controller.drain_status(name)["drained"]:
                    break
                time.sleep(0.05)
            assert cluster.controller.drain_status(name)["drained"], name
            rounds_per_server[name] = used
            # restart: the process dies — a FRESH instance (empty
            # compile registries) comes back under the same name with
            # the same persistent cache dir
            cluster.transport.set_down((name, 0))
            res.set_instance_alive(name, False)
            old.shutdown()
            fresh = ServerInstance(name, max_pending=64)
            fresh.prewarm.timeout_s = prewarm_timeout_s
            starter = ServerStarter(fresh, res, workload_source=workload_source)
            starter.start()
            cluster.transport.register((name, 0), fresh.handle_request)
            cluster.transport.set_down((name, 0), False)
            res.set_instance_alive(name, True)
            cluster.controller.undrain_instance(name)
            cluster.servers[i] = fresh
            cluster.server_starters[i] = starter
            restarted.append(name)
            # recovery: proactive rebalance re-homes load onto the
            # empty restart; phase-2 trims wait for it to finish
            # warming before the surplus source copies drop.  The skew
            # bar drops only for this loop — an empty restart is a
            # ~1.5x skew this topology's default bar would tolerate —
            # so the steady phases stay free of rebalance churn
            default_skew = stab.rebalance_skew_ratio
            stab.rebalance_skew_ratio = 1.2
            used = 0
            while used < max_rounds:
                _mirror_warming(cluster)
                stab.run_once()
                _mirror_warming(cluster)
                hosts = any(
                    name in reps
                    for reps in res.get_ideal_state(physical).values()
                )
                if (
                    hosts
                    and not fresh.prewarm.warming
                    and not stab._pending_moves
                ):
                    break
                used += 1
                time.sleep(0.05)
            stab.rebalance_skew_ratio = default_skew
        time.sleep(0.15)  # steady tail under the recovered fleet
        roll = roll_load.stop()

        state = _replication_state(cluster, physical)
        events = stab.events()
        deferrals = [e for e in events if e["event"] == "rebalanceTrimDeferred"]
        timeouts = [e for e in events if e["event"] == "rebalancePrewarmTimeout"]
        per_server: Dict[str, Dict[str, Any]] = {}
        for s in cluster.servers:
            m = s.metrics.snapshot()["meters"]

            def count(name: str) -> int:
                return int(m.get(name, {}).get("count", 0))

            per_server[s.name] = {
                "compileCold": count("compile.cold"),
                "compileWarm": count("compile.warm"),
                "persistentHits": count("compile.persistentHit"),
                "prewarmed": count("compile.prewarmed"),
                "prewarmCompiled": count("prewarm.compiled"),
                "prewarmFailed": count("prewarm.failed"),
                "laneRestarts": count("lane.restarts"),
                "laneDeviceFailures": count("lane.deviceFailures"),
            }
        restarted_stats = [per_server[n] for n in restarted]
        p99_limit = p99_multiple * max(steady["p99Ms"], p99_floor_ms)
        by_class: Dict[str, int] = {}
        for e in deferrals:
            by_class[e["class"]] = by_class.get(e["class"], 0) + 1
        final = cluster.query(count_pql)
        return {
            "scenario": "rolling-restart-warm",
            "cacheDir": cache_dir,
            "roundsPerServer": rounds_per_server,
            "restarted": restarted,
            "steady": steady,
            **roll,
            **state,
            "servers": per_server,
            "coldCompilesOnRestarted": sum(
                s["compileCold"] for s in restarted_stats
            ),
            "warmStartsOnRestarted": sum(
                s["persistentHits"] + s["prewarmed"] for s in restarted_stats
            ),
            "laneWatchdogClean": all(
                s["laneRestarts"] == 0 and s["laneDeviceFailures"] == 0
                for s in restarted_stats
            ),
            "trimDeferrals": len(deferrals),
            "trimDeferralsByClass": by_class,
            "trimDeferralSample": deferrals[:3],
            "prewarmTimeouts": len(timeouts),
            "prewarmDeferralMeter": int(
                stab.metrics.snapshot()["meters"]
                .get("rebalance.prewarmDeferrals", {})
                .get("count", 0)
            ),
            "steadyP99Ms": steady["p99Ms"],
            "rollP99Ms": roll["p99Ms"],
            "p99LimitMs": round(p99_limit, 3),
            "p99Bounded": roll["p99Ms"] <= p99_limit,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
            "noSegmentLoss": state["replicaSetSizes"] == [replication]
            and final.num_docs_scanned == total
            and not final.partial_response,
        }
    finally:
        for s in cluster.servers:
            s.prewarm.stop()
        cluster.stop()
        if prev_env is None:
            os.environ.pop("PINOT_TPU_COMPILE_CACHE_DIR", None)
        else:
            os.environ["PINOT_TPU_COMPILE_CACHE_DIR"] = prev_env


# ---------------------------------------------------------------------------
# Overload-protection scenarios (ISSUE 7): multi-tenant noisy neighbor
# and ingest backpressure — shared by the CLI and tests/test_overload.py.
# ---------------------------------------------------------------------------


def _tenant_schema(name: str):
    from pinot_tpu.tools.datagen import make_test_schema

    schema = make_test_schema(with_mv=False)
    schema.schema_name = name
    return schema


def run_noisy_neighbor_scenario(
    num_servers: int = 2,
    replication: int = 1,
    num_segments: int = 3,
    clients: int = 3,
    flood_clients: int = 4,
    quota_qps: float = 8.0,
    baseline_s: float = 1.0,
    flood_s: float = 2.5,
    max_pending: int = 16,
    data_dir: Optional[str] = None,
    p99_floor_ms: float = 25.0,
    p99_multiple: float = 3.0,
) -> Dict[str, Any]:
    """Tenant A floods its table while tenant B runs a steady closed
    loop.  The overload plane must contain A end to end:

    - tenant B suffers ZERO failed queries and its p99 stays within a
      fixed multiple of its unloaded baseline (measured first);
    - tenant A's overflow is shed with TYPED errors (429 at the broker
      admission tiers, 210 at the server fair-share scheduler) — never
      client-visible timeouts;
    - the quota lands through the LIVE update path
      (``update_table_quota``), as a production operator would apply it.
    """
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import random_rows

    cluster = InProcessCluster(
        num_servers=num_servers, data_dir=data_dir, max_pending=max_pending
    )
    try:
        totals: Dict[str, int] = {}
        physicals: Dict[str, str] = {}
        for tenant in ("tenantA", "tenantB"):
            schema = _tenant_schema(tenant)
            physical = cluster.add_offline_table(schema, replication=replication)
            physicals[tenant] = physical
            rows = random_rows(schema, 240, seed=7)
            total = 0
            for i in range(num_segments):
                n = 40 + 30 * (i % 3)
                cluster.upload(
                    physical, build_segment(schema, rows[:n], physical, f"{tenant}s{i}")
                )
                total += n
            totals[tenant] = total

        pql_a = "SELECT count(*) FROM tenantA"
        pql_b = "SELECT count(*) FROM tenantB"
        # warm both paths (staging + plan build) before measuring
        for pql in (pql_a, pql_b):
            r = cluster.broker.handle_pql(pql)
            assert not r.exceptions, r.exceptions

        # phase 1: tenant B's unloaded baseline
        base_load = ClosedLoopLoad(cluster, pql_b, totals["tenantB"], clients).start()
        time.sleep(baseline_s)
        baseline = base_load.stop()

        # phase 2: quota lands on tenant A through the LIVE update path
        cluster.controller.resources.update_table_quota(
            physicals["tenantA"], quota_qps
        )

        # phase 3: A floods (open throttle, >> 10x quota offered) while
        # B keeps its steady closed loop
        b_load = ClosedLoopLoad(cluster, pql_b, totals["tenantB"], clients).start()
        a_flood = FloodLoad(cluster, pql_a, clients=flood_clients).start()
        time.sleep(flood_s)
        a_summary = a_flood.stop()
        b_summary = b_load.stop()

        baseline_p99 = baseline["p99Ms"]
        loaded_p99 = b_summary["p99Ms"]
        # absolute floor absorbs scheduler jitter on a near-zero
        # baseline: 3x of 2ms is not a meaningful isolation bar.
        # Callers on CPU-starved boxes (the 2-core CI container under
        # full-suite load) widen floor/multiple rather than compare
        # wall clock against a baseline measured in a quieter window.
        p99_limit = p99_multiple * max(baseline_p99, p99_floor_ms)
        offered_qps = a_summary["queries"] / max(flood_s, 1e-9)
        return {
            "scenario": "noisy-neighbor",
            "quotaQps": quota_qps,
            "offeredQpsA": round(offered_qps, 1),
            "offeredMultiple": round(offered_qps / quota_qps, 1),
            "tenantA": a_summary,
            "tenantB": b_summary,
            "tenantBBaseline": baseline,
            "tenantBLoadedP99Ms": loaded_p99,
            "tenantBP99LimitMs": round(p99_limit, 3),
            "tenantBP99Within": loaded_p99 <= p99_limit,
            "sheddingTyped": a_summary["timeouts"] == 0
            and a_summary["otherFailures"] == 0,
            "admission": cluster.broker.admission.snapshot(),
            "scheduler": {
                s.name: s.scheduler.stats() for s in cluster.servers
            },
            # main()'s exit-code contract: any tenant-B failure OR any
            # untyped tenant-A overflow fails the scenario
            "failedQueries": b_summary["failedQueries"]
            + a_summary["timeouts"]
            + a_summary["otherFailures"],
        }
    finally:
        cluster.stop()


def run_join_under_flood_scenario(
    num_servers: int = 2,
    replication: int = 1,
    clients: int = 3,
    flood_clients: int = 4,
    quota_qps: float = 8.0,
    baseline_s: float = 1.0,
    flood_s: float = 2.5,
    max_pending: int = 16,
    data_dir: Optional[str] = None,
    p99_floor_ms: float = 25.0,
    p99_multiple: float = 3.0,
) -> Dict[str, Any]:
    """ISSUE 14 chaos: tenant A floods two-table JOINs at >>10x its
    quota while tenant B runs steady scans.  Joins fan out into
    multi-phase scatter traffic (extracts + exchange), so this proves
    the join plane rides the overload machinery end to end:

    - the broker admission front door sheds A's overflow BEFORE any
      join phase scatters (429s, typed);
    - the phase requests that do run queue under tenant A's tables in
      the server fair-share scheduler, so B's p99 holds within a fixed
      multiple of its unloaded baseline;
    - tenant B suffers ZERO failed queries.
    """
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import random_rows

    cluster = InProcessCluster(
        num_servers=num_servers, data_dir=data_dir, max_pending=max_pending
    )
    try:
        from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema

        fact_schema = Schema(
            "aFact",
            dimensions=[FieldSpec("k", DataType.INT, FieldType.DIMENSION)],
            metrics=[FieldSpec("v", DataType.INT, FieldType.METRIC)],
        )
        dim_schema = Schema(
            "aDim",
            dimensions=[FieldSpec("k", DataType.INT, FieldType.DIMENSION)],
            metrics=[FieldSpec("w", DataType.INT, FieldType.METRIC)],
        )
        fact_phys = cluster.add_offline_table(fact_schema, replication=replication)
        dim_phys = cluster.add_offline_table(dim_schema, replication=replication)
        import numpy as _np

        rng = _np.random.default_rng(11)
        for i in range(2):
            frows = [
                {"k": int(k), "v": int(v)}
                for k, v in zip(rng.integers(0, 60, 150), rng.integers(0, 99, 150))
            ]
            cluster.upload(
                fact_phys, build_segment(fact_schema, frows, fact_phys, f"aFact_s{i}")
            )
        cluster.upload(
            dim_phys,
            build_segment(
                dim_schema,
                [{"k": k, "w": k * 2} for k in range(60)],
                dim_phys,
                "aDim_s0",
            ),
        )
        schema_b = _tenant_schema("tenantB")
        phys_b = cluster.add_offline_table(schema_b, replication=replication)
        rows_b = random_rows(schema_b, 240, seed=7)
        total_b = 0
        for i in range(3):
            n = 40 + 30 * (i % 3)
            cluster.upload(
                phys_b, build_segment(schema_b, rows_b[:n], phys_b, f"tenantBs{i}")
            )
            total_b += n

        pql_join = "SELECT count(*), sum(f.v) FROM aFact f JOIN aDim d ON f.k = d.k"
        pql_b = "SELECT count(*) FROM tenantB"
        for pql in (pql_join, pql_b):
            r = cluster.broker.handle_pql(pql)
            assert not r.exceptions, r.exceptions

        base_load = ClosedLoopLoad(cluster, pql_b, total_b, clients).start()
        time.sleep(baseline_s)
        baseline = base_load.stop()

        # quota lands on the join's LEFT table through the live path —
        # the broker admission front door keys joins on it
        cluster.controller.resources.update_table_quota(fact_phys, quota_qps)

        b_load = ClosedLoopLoad(cluster, pql_b, total_b, clients).start()
        a_flood = FloodLoad(cluster, pql_join, clients=flood_clients).start()
        time.sleep(flood_s)
        a_summary = a_flood.stop()
        b_summary = b_load.stop()

        baseline_p99 = baseline["p99Ms"]
        loaded_p99 = b_summary["p99Ms"]
        p99_limit = p99_multiple * max(baseline_p99, p99_floor_ms)
        offered_qps = a_summary["queries"] / max(flood_s, 1e-9)
        return {
            "scenario": "join-under-flood",
            "quotaQps": quota_qps,
            "offeredQpsA": round(offered_qps, 1),
            "offeredMultiple": round(offered_qps / quota_qps, 1),
            "tenantA": a_summary,
            "tenantB": b_summary,
            "tenantBBaseline": baseline,
            "tenantBLoadedP99Ms": loaded_p99,
            "tenantBP99LimitMs": round(p99_limit, 3),
            "tenantBP99Within": loaded_p99 <= p99_limit,
            "sheddingTyped": a_summary["timeouts"] == 0
            and a_summary["otherFailures"] == 0,
            "joinMeters": {
                k: v["count"]
                for k, v in cluster.broker.metrics.snapshot()
                .get("meters", {})
                .items()
                if k.startswith("join.")
            },
            "failedQueries": b_summary["failedQueries"]
            + a_summary["timeouts"]
            + a_summary["otherFailures"],
        }
    finally:
        cluster.stop()


def run_ingest_backpressure_scenario(
    rows: int = 400,
    rows_per_segment: int = 1000,
    hbm_high_bytes: float = 256.0,
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Prove the ingest watermark contract end to end: a consumer
    pauses when the HBM staging ledger crosses the high watermark
    (query-driven staging — the 'query flood squeezes ingest' shape),
    its offset freezes while lag stays visible, and after the pressure
    clears it resumes and drains lag to 0 — no rows lost or skipped."""
    from pinot_tpu.engine.device import LEDGER, clear_staging_cache
    from pinot_tpu.realtime.backpressure import IngestBackpressure
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.stream import MemoryStreamProvider
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    clear_staging_cache()  # start from a known-empty ledger
    cluster = InProcessCluster(num_servers=1, data_dir=data_dir)
    try:
        server = cluster.servers[0]
        # tight watermarks wired to the REAL staging ledger, installed
        # BEFORE the consumer exists so it binds to this governor
        server.ingest_backpressure = IngestBackpressure(
            metrics=server.metrics,
            hbm_high_bytes=hbm_high_bytes,
            hbm_low_bytes=hbm_high_bytes / 2.0,
            poll_interval_s=0.0,
        )

        # an offline table whose staging will push the ledger over the
        # high watermark (the query side of the squeeze)
        offline_schema = _tenant_schema("pressure")
        offline_physical = cluster.add_offline_table(offline_schema)
        cluster.upload(
            offline_physical,
            build_segment(
                offline_schema, random_rows(offline_schema, 200, seed=3),
                offline_physical, "p0",
            ),
        )

        rt_schema = _tenant_schema("rtTable")
        stream = MemoryStreamProvider(num_partitions=1)
        physical = cluster.add_realtime_table(
            rt_schema, stream, rows_per_segment=rows_per_segment
        )
        for row in random_rows(rt_schema, rows, seed=5):
            stream.produce(row)
        dm = cluster.controller.realtime_manager.consumers_of(
            make_segment_name(physical, 0, 0)
        )[0]

        # phase 1: unpressured consumption advances
        consumed_free = dm.consume_step(max_rows=100)

        # phase 2: a query stages the offline table's columns -> ledger
        # crosses the high watermark -> the consumer PAUSES (offset
        # frozen).  A group-by aggregation stages forward + dictionary
        # arrays (a bare count(*) would stage only the doc counts).
        cluster.query("SELECT sum(metInt) FROM pressure GROUP BY dimStr TOP 5")
        staged_bytes = LEDGER.total_bytes()
        paused_consumed = dm.consume_step(max_rows=100)
        offset_at_pause = dm.offset
        dm.consume_step(max_rows=100)  # still paused: offset must not move
        paused_state = {
            "paused": server.ingest_backpressure.paused,
            "reason": server.ingest_backpressure.reason,
            "lagWhilePaused": dm.lag(),
            "offsetFrozen": dm.offset == offset_at_pause,
        }

        # phase 3: pressure clears -> resume -> lag drains to 0
        clear_staging_cache()
        drained = 0
        for _ in range(200):
            got = dm.consume_step(max_rows=100)
            drained += got
            if dm.lag() == 0:
                break
        return {
            "scenario": "ingest-backpressure",
            "hbmHighBytes": hbm_high_bytes,
            "stagedBytesAtPause": staged_bytes,
            "consumedBeforePressure": consumed_free,
            "consumedWhilePaused": paused_consumed,
            **paused_state,
            "resumed": not server.ingest_backpressure.paused,
            "consumedAfterResume": drained,
            "finalLag": dm.lag(),
            "governor": server.ingest_backpressure.snapshot(),
            "failedQueries": 0
            if (
                paused_state["offsetFrozen"]
                and paused_consumed == 0
                and dm.lag() == 0
            )
            else 1,
        }
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# HBM-pressure scenario (ISSUE 18): addressable staged data ~8x the
# residency HBM cap under closed-loop mixed load — the tiered
# residency manager (engine/residency.py) must keep the hot set
# resident while cold tables cycle HBM <-> host <-> disk, and an
# injected allocation failure must heal by demotion, never by
# poisoning the plan.  Shared by the CLI and
# tests/test_chaos_hbm_pressure.py.
# ---------------------------------------------------------------------------


def run_hbm_pressure_scenario(
    num_tables: int = 10,
    rows_per_table: int = 96,
    clients: int = 3,
    baseline_s: float = 1.0,
    load_s: float = 4.0,
    data_dir: Optional[str] = None,
    seed: int = 421,
) -> Dict[str, Any]:
    """One server hosting ``num_tables`` identical tables whose total
    staged footprint is ~8x the HBM cap the scenario then imposes:

    - a hot table runs a closed loop while a sweeper cycles queries
      over every cold table, forcing continuous demotion (hot tier
      over cap), spill (warm tier over host cap) and promotion (cold
      tables re-queried) — the counters must prove all three tiers
      cycled, with ZERO failed queries and byte-exact counts;
    - the hot set stays protected: its p99 under pressure is compared
      against its own uncapped baseline (heat scoring must keep the
      closed-loop table out of the victim pool);
    - a seeded allocation failure (``DeviceFaultInjector
      .alloc_fail_next``) lands on a hot query mid-pressure: the
      executor must classify RESOURCE_EXHAUSTED, demote, retry and
      answer correctly — ``heal.resourceExhausted`` marks, nothing is
      poisoned, no host failover.

    Caps are measured, not assumed: the per-table footprint comes from
    the staging ledger delta of the first stage, so the scenario holds
    its ~8x oversubscription on any platform/dtype.
    """
    from pinot_tpu.common.faults import DeviceFaultInjector
    from pinot_tpu.engine.device import LEDGER, clear_staging_cache
    from pinot_tpu.engine.residency import RESIDENCY
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import random_rows

    saved_env = {
        k: os.environ.get(k)
        for k in ("PINOT_TPU_HBM_CAP_BYTES", "PINOT_TPU_HOST_CAP_BYTES")
    }
    clear_staging_cache()  # measured footprints start from zero
    cluster = InProcessCluster(num_servers=1, data_dir=data_dir)
    try:
        names = [f"tierT{i}" for i in range(num_tables)]
        totals: Dict[str, int] = {}
        for name in names:
            schema = _tenant_schema(name)
            physical = cluster.add_offline_table(schema, replication=1)
            rows = random_rows(schema, rows_per_table, seed=seed)
            half = rows_per_table // 2
            cluster.upload(
                physical, build_segment(schema, rows[:half], physical, f"{name}s0")
            )
            cluster.upload(
                physical, build_segment(schema, rows[half:], physical, f"{name}s1")
            )
            totals[name] = rows_per_table

        hot = names[0]

        # aggregation over several columns so each table stages a real
        # packed footprint (a bare count(*) stages only the num-docs
        # array and would make the byte caps meaningless)
        def pql_for(name: str) -> str:
            return (
                "SELECT sum(metInt), sum(metFloat), sum(metDouble), "
                f"max(dimInt), max(dimLong) FROM {name} GROUP BY dimStr"
            )

        hot_pql = pql_for(hot)

        # measure the per-table staged footprint off the first stage's
        # ledger delta, then warm every table so "addressable" is the
        # real uncapped total
        before = LEDGER.total_bytes()
        r = cluster.broker.handle_pql(hot_pql)
        assert not r.exceptions, r.exceptions
        table_bytes = max(1, int(LEDGER.total_bytes() - before))
        for name in names[1:]:
            r = cluster.broker.handle_pql(pql_for(name))
            assert not r.exceptions, r.exceptions
        addressable = int(LEDGER.total_bytes())

        # phase 1: the hot table's UNCAPPED baseline
        base = ClosedLoopLoad(cluster, hot_pql, totals[hot], clients).start()
        time.sleep(baseline_s)
        baseline = base.stop()

        # phase 2: impose the caps — hot tier fits ~1.25 tables
        # (addressable/cap ~= 8x for the default 10 tables), warm tier
        # ~2.5 more, the rest lives on disk
        cap = max(1, int(table_bytes * num_tables / 8.0))
        os.environ["PINOT_TPU_HBM_CAP_BYTES"] = str(cap)
        os.environ["PINOT_TPU_HOST_CAP_BYTES"] = str(int(table_bytes * 2.5))
        # apply the new cap to the already-resident set (enforcement
        # otherwise runs on staging inserts, and everything is cached):
        # the operator's cap change takes effect immediately
        RESIDENCY.enforce()
        counters0 = {
            n: RESIDENCY.counter(n)
            for n in ("demotions", "promotions", "coldDemotions", "coldLoads")
        }

        # phase 3: hot closed loop + cold-table sweeper, concurrently
        stop = threading.Event()
        sweep_errors: List[str] = []
        sweeps = [0]

        def sweeper() -> None:
            i = 0
            while not stop.is_set():
                name = names[1 + (i % (num_tables - 1))]
                i += 1
                try:
                    resp = cluster.broker.handle_pql(pql_for(name))
                except Exception as e:
                    sweep_errors.append(f"{name}: {type(e).__name__}: {e}")
                    continue
                sweeps[0] += 1
                if resp.exceptions or resp.num_docs_scanned != totals[name]:
                    if len(sweep_errors) < 8:
                        sweep_errors.append(
                            f"{name}: docs={resp.num_docs_scanned}/{totals[name]} "
                            f"exc={[e.message for e in resp.exceptions][:2]}"
                        )

        hot_load = ClosedLoopLoad(cluster, hot_pql, totals[hot], clients).start()
        sweep_thread = threading.Thread(target=sweeper, daemon=True)
        sweep_thread.start()
        time.sleep(load_s)
        stop.set()
        hot_summary = hot_load.stop()
        sweep_thread.join(timeout=10)

        # phase 4: seeded allocation failure on a hot query, still
        # under pressure — must heal by demotion, never poison
        server = cluster.servers[0]
        inj = DeviceFaultInjector(seed=seed)
        lanes = server.lanes.lanes if server.lanes is not None else []
        for lane in lanes:
            lane.fault_injector = inj
        heal_before = dict(server.executor.healing_stats())
        inj.alloc_fail_next(1)
        try:
            resp = cluster.broker.handle_pql(hot_pql)
        finally:
            for lane in lanes:
                lane.fault_injector = None
        heal_after = dict(server.executor.healing_stats())
        oom_healed = (
            not resp.exceptions
            and resp.num_docs_scanned == totals[hot]
            and heal_after["resourceExhausted"]
            > heal_before["resourceExhausted"]
            and heal_after["hostFailovers"] == heal_before["hostFailovers"]
            and heal_after["poisonedPlans"] == 0
        )

        deltas = {
            n: RESIDENCY.counter(n) - counters0[n] for n in counters0
        }
        import jax

        hot_p99 = hot_summary["p99Ms"]
        base_p99 = baseline["p99Ms"]
        failed = (
            hot_summary["failedQueries"]
            + len(sweep_errors)
            + (0 if oom_healed else 1)
        )
        return {
            "scenario": "hbm-pressure",
            "metric": "tiered_hbm_pressure",
            "value": round(addressable / cap, 3),
            "addressable_over_cap": round(addressable / cap, 3),
            "num_tables": num_tables,
            "platform": jax.default_backend(),
            "tableBytes": table_bytes,
            "addressableBytes": addressable,
            "hbmCapBytes": cap,
            "hot_p99_ms": hot_p99,
            "baseline_p99_ms": base_p99,
            "hot_p99_over_baseline": round(hot_p99 / max(base_p99, 1e-3), 3),
            "demotions": deltas["demotions"],
            "promotions": deltas["promotions"],
            "cold_demotions": deltas["coldDemotions"],
            "cold_loads": deltas["coldLoads"],
            "coldSweeps": sweeps[0],
            "hotLoad": hot_summary,
            "hotBaseline": baseline,
            "sweepErrors": sweep_errors,
            "oomHealed": oom_healed,
            "selfHealing": heal_after,
            "residency": RESIDENCY.snapshot(),
            "failedQueries": failed,
        }
    finally:
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        clear_staging_cache()  # cap-era residue must not leak to callers


# ---------------------------------------------------------------------------
# Audit-divergence scenario (ISSUE 19): a seeded fault injector makes
# one serving tier return silently-wrong aggregates under closed-loop
# load — the shadow differential auditor must catch it, quarantine the
# (plan digest, tier), and the cluster must keep answering byte-
# correctly (served off the quarantined tier) with ZERO failed queries.
# Shared by the CLI and tests/test_audit.py.
# ---------------------------------------------------------------------------


def run_audit_divergence_scenario(
    num_segments: int = 2,
    rows: int = 96,
    clients: int = 2,
    load_s: float = 2.0,
    detect_budget_s: float = 12.0,
    corrupt_n: int = 3,
    data_dir: Optional[str] = None,
    seed: int = 1907,
) -> Dict[str, Any]:
    """One server, one offline table, a closed query loop — and a
    seeded ``DeviceFaultInjector.corrupt_results`` that perturbs the
    next ``corrupt_n`` served aggregates on whatever non-host tier
    answers.  The corruption raises no exception, so the self-healing
    ladder (PR 3) can never see it: only the shadow differential
    auditor can.  Acceptance:

    - the divergence is DETECTED (``audit.divergences``) within
      ``detect_budget_s`` and the (plan digest, tier) is quarantined;
    - every query after the quarantine is byte-identical (accounting
      stripped) to the pre-corruption reference — the quarantined tier
      is steered around, not retried;
    - zero failed queries: the wrong answers themselves complete
      without exceptions (that is the point), and nothing else breaks.
    """
    from pinot_tpu.common.faults import DeviceFaultInjector
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import random_rows
    from pinot_tpu.utils.audit import (
        SamplerBudget,
        payloads_equivalent,
        strip_accounting,
    )

    # sample every completed query with an effectively-unmetered private
    # budget so detection latency measures the audit loop, not the
    # sampler (the process-wide default budget stays untouched)
    saved_env = {
        k: os.environ.get(k) for k in ("PINOT_TPU_AUDIT_SAMPLE_N",)
    }
    os.environ["PINOT_TPU_AUDIT_SAMPLE_N"] = "1"
    cluster = InProcessCluster(num_servers=1, data_dir=data_dir)
    inj = DeviceFaultInjector(seed=seed)
    server = cluster.servers[0]
    server.auditor.budget = SamplerBudget(per_s=1000.0, burst=64.0)
    lanes = server.lanes.lanes if server.lanes is not None else []
    try:
        schema = _tenant_schema("auditT")
        physical = cluster.add_offline_table(schema, replication=1)
        all_rows = random_rows(schema, rows, seed=seed)
        per = max(1, rows // num_segments)
        for i in range(num_segments):
            chunk = all_rows[i * per:(i + 1) * per] or all_rows[-per:]
            cluster.upload(
                physical, build_segment(schema, chunk, physical, f"audits{i}")
            )
        pql = (
            "SELECT sum(metInt), sum(metFloat), max(dimInt) "
            "FROM auditT GROUP BY dimStr"
        )

        # pre-corruption reference payload (accounting stripped — the
        # same strip the auditor itself compares under)
        ref_resp = cluster.broker.handle_pql(pql)
        assert not ref_resp.exceptions, ref_resp.exceptions
        reference = strip_accounting(ref_resp.to_json())
        expected_docs = ref_resp.num_docs_scanned

        load = ClosedLoopLoad(cluster, pql, expected_docs, clients).start()
        time.sleep(min(0.5, load_s))  # steady state before the fault

        for lane in lanes:
            lane.fault_injector = inj
        if not lanes and server.executor.lane is not None:
            server.executor.lane.fault_injector = inj
        # delta sized to dominate the auditor's float32-accumulation
        # tolerance band on these group sums by orders of magnitude — a
        # "wrong answer" here must be unambiguously wrong, not a rounding
        # argument (payloads_equivalent rel_tol is 5e-4)
        inj.corrupt_results(n=corrupt_n, delta=100.0)
        armed_at = time.monotonic()

        # wait for the audit plane to catch it
        detected_s: Optional[float] = None
        quarantined: List[Dict[str, Any]] = []
        while time.monotonic() - armed_at < detect_budget_s:
            quarantined = server.executor.audit_quarantined_snapshot()
            if quarantined:
                detected_s = time.monotonic() - armed_at
                break
            time.sleep(0.05)
        inj.heal()  # unfired corruption budget must not leak forward

        time.sleep(min(1.0, load_s))  # post-quarantine serving window
        summary = load.stop()

        # correctness after quarantine: repeated answers must be
        # byte-identical to EACH OTHER (one tier serves now — no
        # flapping) and equivalent to the pre-corruption reference
        # (float32-vs-float64 accumulation tolerance only; the injected
        # delta is orders of magnitude larger)
        post_mismatches = 0
        post_baseline = None
        for _ in range(8):
            resp = cluster.broker.handle_pql(pql)
            payload = strip_accounting(resp.to_json())
            if post_baseline is None:
                post_baseline = payload
            if (
                resp.exceptions
                or payload != post_baseline
                or not payloads_equivalent(payload, reference)
            ):
                post_mismatches += 1
        audit_snap = server.auditor.snapshot()
        heal = server.executor.healing_stats()
        divergences = audit_snap["divergences"]
        recent = audit_snap.get("recentDivergences") or []
        detect_ms = max((d.get("detectMs") or 0.0) for d in recent) if recent else None

        failed = (
            summary["failedQueries"]
            + (0 if detected_s is not None else 1)
            + post_mismatches
        )
        return {
            "scenario": "audit-divergence",
            "metric": "audit_detect_s",
            "value": round(detected_s, 3) if detected_s is not None else None,
            "detected": detected_s is not None,
            "detectWallS": round(detected_s, 3) if detected_s is not None else None,
            "detectMs": detect_ms,
            "divergences": divergences,
            "quarantined": quarantined,
            "auditTierSkips": heal.get("auditTierSkips", 0),
            "postQuarantineMismatches": post_mismatches,
            "load": summary,
            "audit": audit_snap,
            "failedQueries": failed,
        }
    finally:
        for lane in lanes:
            lane.fault_injector = None
        if server.executor.lane is not None:
            server.executor.lane.fault_injector = None
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# Elastic-fleet scenario (ISSUE 15): 100+ tables under mixed
# ingest+query closed-loop load, a forced hot-tenant skew, a live
# make-before-break rebalance, and a mid-rebalance controller restart.
# Shared by the CLI and tests/test_elastic_fleet.py.
# ---------------------------------------------------------------------------


def _fleet_loads_by_server(res, tables) -> Dict[str, float]:
    """Doc-weighted ideal-state load per server (the scenario's own
    balance check — deliberately independent of the planner's)."""
    load: Dict[str, float] = {}
    for table in tables:
        for seg, replicas in res.get_ideal_state(table).items():
            info = res.get_segment_metadata(table, seg)
            meta = info.get("metadata") if info else None
            docs = max(1, int(getattr(meta, "num_docs", 0) or 0))
            for s in replicas:
                load[s] = load.get(s, 0.0) + docs
    return load


def run_elastic_fleet_scenario(
    num_tables: int = 104,
    num_servers: int = 3,
    clients: int = 3,
    hot_segments: int = 6,
    hot_docs: int = 400,
    fleet_docs: int = 20,
    rt_tables: int = 2,
    rt_partitions: int = 2,
    rows_per_segment: int = 40,
    rt_segments_per_partition: int = 2,
    pool_workers: int = 4,
    max_rounds: int = 40,
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """The elastic-fleet chaos acceptance (ISSUE 15), end to end:

    1. **breadth** — ``num_tables`` tables on ``num_servers`` servers:
       mostly tiny offline tables (the 100-tenant fleet), plus
       ``rt_tables`` REALTIME tables whose partitions are consumed by
       the shared ``IngestConsumerPool`` (partition-parallel ingest);
    2. **mixed load** — closed-loop query clients over a fleet table,
       the hot table, and a live realtime table WHILE ingest runs;
    3. **forced skew** — ``hot_segments`` doc-heavy segments pinned
       onto server0 plus a cost-rate hint naming the hot table, so the
       stabilizer's skew evaluation must trip;
    4. **live rebalance** — the planner's make-before-break moves run
       under load; every round asserts no segment ever loses its last
       serving replica (coverage is checked against the external view,
       not hoped for);
    5. **mid-rebalance controller restart** — with moves still pending,
       the controller is torn down and a NEW incarnation recovers from
       the property store; servers and the broker re-wire to it and its
       stabilizer completes the remaining moves from DERIVED state.

    Acceptance: zero failed queries end to end, zero lost/duplicate
    rows (realtime counts exact), exactly one committed copy per
    (partition, sequence), and a final placement whose doc-weighted
    imbalance is back under the skew threshold.
    """
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.pool import IngestConsumerPool
    from pinot_tpu.realtime.stream import MemoryStreamProvider
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.server.starter import ServerStarter
    from pinot_tpu.tools.datagen import random_rows

    cluster = InProcessCluster(num_servers=num_servers, data_dir=data_dir)
    ctrl_a = cluster.controller
    res = ctrl_a.resources
    st = ctrl_a.stabilizer
    st.grace_s = 0.0
    # tight knobs so the scenario converges in bounded rounds (defaults
    # are production-paced: ratio 2.0, 3 rounds, 2 moves)
    st.rebalance_skew_ratio = 1.4
    st.rebalance_hysteresis = 2
    st.rebalance_max_moves = 4

    pool_a = IngestConsumerPool(workers=pool_workers, name="elasticA")
    ctrl_a.realtime_manager.ingest_pool = pool_a

    ctrl_b: Optional[Controller] = None
    pool_b: Optional[IngestConsumerPool] = None
    loads: List[ClosedLoopLoad] = []
    try:
        # -- 1. breadth: the 100-table fleet --------------------------
        template = _tenant_schema("fleet0")
        fleet_rows = random_rows(template, fleet_docs, seed=13)
        hot_rows = random_rows(template, hot_docs, seed=14)
        num_offline = num_tables - rt_tables - 1  # -1: the hot table
        fleet_physicals: List[str] = []
        for i in range(num_offline):
            schema = _tenant_schema(f"fleet{i}")
            physical = cluster.add_offline_table(schema, replication=1)
            fleet_physicals.append(physical)
            cluster.upload(
                physical,
                build_segment(schema, fleet_rows, physical, f"fleet{i}s0"),
            )

        # -- realtime tables on the shared consumer pool --------------
        rt_rows_per_partition = rows_per_segment * rt_segments_per_partition
        rt_physicals: List[str] = []
        rt_streams: List[MemoryStreamProvider] = []
        for i in range(rt_tables):
            schema = _tenant_schema(f"rtFleet{i}")
            stream = MemoryStreamProvider(num_partitions=rt_partitions)
            physical = cluster.add_realtime_table(
                schema, stream, rows_per_segment=rows_per_segment
            )
            rt_physicals.append(physical)
            rt_streams.append(stream)
            rows = random_rows(schema, rt_rows_per_partition, seed=20 + i)
            for p in range(rt_partitions):
                for row in rows:
                    stream.produce(row, partition=p)

        # -- forced hot-tenant skew -----------------------------------
        hot_schema = _tenant_schema("hotTable")
        hot_physical = cluster.add_offline_table(hot_schema, replication=1)
        for i in range(hot_segments):
            seg = build_segment(hot_schema, hot_rows, hot_physical, f"hot{i}")
            path = ctrl_a.store.save(hot_physical, seg)
            res.add_segment(
                hot_physical, seg.metadata,
                {"dir": path, "downloadUri": "file://" + os.path.abspath(path)},
                servers=["server0"],
            )
        # the cost axis: the hot table is also the hot QUERY tenant
        # (what /debug/capacity would report once brokers attribute it)
        st.cost_rate_fn = lambda: {"hotTable": 50.0}

        expected_hot = hot_segments * hot_docs
        expected_fleet = fleet_docs
        total_rt = rt_partitions * rt_rows_per_partition

        # -- 2. mixed ingest+query closed-loop load -------------------
        loads = [
            ClosedLoopLoad(
                cluster, "SELECT count(*) FROM hotTable", expected_hot, clients
            ).start(),
            ClosedLoopLoad(
                cluster, "SELECT count(*) FROM fleet0", expected_fleet, 1
            ).start(),
            # live realtime table: any complete answer is correct while
            # ingest advances the count
            ClosedLoopLoad(
                cluster, "SELECT count(*) FROM rtFleet0", None, 1
            ).start(),
        ]
        time.sleep(0.2)

        def coverage_ok(r=None) -> bool:
            """No segment may ever lose its last serving replica (checked
            against whichever controller incarnation owns the round)."""
            r = r or res
            for table in [hot_physical] + fleet_physicals[:3]:
                view = r.get_external_view(table)
                for seg, replicas in r.get_ideal_state(table).items():
                    if not any(
                        view.get(seg, {}).get(s) == "ONLINE" for s in replicas
                    ):
                        return False
            return True

        # -- 4. live rebalance, stopped MID-flight --------------------
        coverage_never_lost = True
        moves_started_at_restart = 0
        rounds_a = 0
        for _ in range(max_rounds):
            st.run_once()
            rounds_a += 1
            coverage_never_lost = coverage_never_lost and coverage_ok()
            moves_started_at_restart = st.metrics.meter(
                "rebalance.movesStarted"
            ).count
            if moves_started_at_restart and st._pending_moves:
                break  # mid-rebalance: phase-1 done, phase-2 pending
            time.sleep(0.02)
        pending_at_restart = len(st._pending_moves)
        surplus_at_restart = sum(
            1
            for table in [hot_physical] + fleet_physicals
            for replicas in res.get_ideal_state(table).values()
            if len(replicas) > 1
        )

        # realtime must be quiescent before the in-process restart (a
        # MEMORY stream's buffered rows die with the manager, so the
        # tip consumer must be empty = everything produced is durable)
        def rt_quiescent() -> bool:
            for physical in rt_physicals:
                ideal = res.get_ideal_state(physical)
                for p in range(rt_partitions):
                    for seq in range(rt_segments_per_partition):
                        seg = ideal.get(make_segment_name(physical, p, seq))
                        if not seg or "ONLINE" not in seg.values():
                            return False
            return True

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not rt_quiescent():
            time.sleep(0.05)
        rt_committed = rt_quiescent()

        # -- 5. mid-rebalance controller restart ----------------------
        pool_a.stop()
        ctrl_a.stop()
        ctrl_b = Controller(cluster.data_dir)
        ctrl_b.stabilizer.grace_s = 0.0
        ctrl_b.stabilizer.rebalance_skew_ratio = st.rebalance_skew_ratio
        ctrl_b.stabilizer.rebalance_hysteresis = st.rebalance_hysteresis
        ctrl_b.stabilizer.rebalance_max_moves = st.rebalance_max_moves
        ctrl_b.stabilizer.cost_rate_fn = st.cost_rate_fn
        pool_b = IngestConsumerPool(workers=pool_workers, name="elasticB")
        ctrl_b.realtime_manager.ingest_pool = pool_b
        # servers first (their replays refill B's external views), THEN
        # the broker (which re-seeds routing from those views) — the
        # broker serves from its last routing meanwhile, and since
        # make-before-break never dropped a serving replica, no query
        # has anywhere to fail
        for server in cluster.servers:
            ServerStarter(server, ctrl_b.resources).start()
        BrokerStarter(cluster.broker, ctrl_b.resources).start()

        st_b = ctrl_b.stabilizer
        rounds_b = 0
        for _ in range(max_rounds):
            st_b.run_once()
            rounds_b += 1
            coverage_never_lost = coverage_never_lost and coverage_ok(
                ctrl_b.resources
            )
            surplus = sum(
                1
                for table in [hot_physical] + fleet_physicals
                for replicas in ctrl_b.resources.get_ideal_state(table).values()
                if len(replicas) > 1
            )
            if (
                surplus == 0
                and not st_b._pending_moves
                and st_b.metrics.gauge("rebalance.imbalanceRatio").value
                < st_b.rebalance_skew_ratio
            ):
                break
            time.sleep(0.02)
        time.sleep(0.2)
        summaries = [load.stop() for load in loads]
        loads = []

        # -- acceptance accounting ------------------------------------
        res_b = ctrl_b.resources
        final_hot = cluster.query("SELECT count(*) FROM hotTable")
        final_rt = [
            cluster.query(f"SELECT count(*) FROM rtFleet{i}")
            for i in range(rt_tables)
        ]
        rt_counts = [r.num_docs_scanned for r in final_rt]
        # exactly one committed copy per (partition, sequence): the
        # ideal state holds exactly the expected segment names, each
        # committed one with exactly one ONLINE replica
        one_copy_per_seq = True
        for physical in rt_physicals:
            ideal = res_b.get_ideal_state(physical)
            expected_names = set()
            for p in range(rt_partitions):
                for seq in range(rt_segments_per_partition):
                    name = make_segment_name(physical, p, seq)
                    expected_names.add(name)
                    replicas = ideal.get(name, {})
                    if list(replicas.values()).count("ONLINE") != 1:
                        one_copy_per_seq = False
                # the tip consuming segment (one per partition)
                expected_names.add(
                    make_segment_name(physical, p, rt_segments_per_partition)
                )
            if set(ideal) != expected_names:
                one_copy_per_seq = False

        balance = _fleet_loads_by_server(
            res_b, [hot_physical] + fleet_physicals
        )
        mean_load = sum(balance.values()) / max(1, len(balance))
        final_ratio = (
            max(balance.values()) / mean_load if mean_load > 0 else 0.0
        )

        failed = sum(s["failedQueries"] for s in summaries)
        rt_exact = rt_counts == [total_rt] * rt_tables
        ok = (
            failed == 0
            and coverage_never_lost
            and rt_committed
            and rt_exact
            and one_copy_per_seq
            and moves_started_at_restart > 0
            and (pending_at_restart > 0 or surplus_at_restart > 0)
            and final_ratio < st.rebalance_skew_ratio
            and final_hot.num_docs_scanned == expected_hot
            and not final_hot.exceptions
        )
        return {
            "scenario": "elastic-fleet",
            "tables": num_tables,
            "servers": num_servers,
            "load": summaries,
            "queries": sum(s["queries"] for s in summaries),
            "okQueries": sum(s["okQueries"] for s in summaries),
            "partialQueries": sum(s["partialQueries"] for s in summaries),
            "failures": [f for s in summaries for f in s["failures"]],
            "roundsBeforeRestart": rounds_a,
            "roundsAfterRestart": rounds_b,
            "movesStartedBeforeRestart": moves_started_at_restart,
            "pendingMovesAtRestart": pending_at_restart,
            "surplusReplicasAtRestart": surplus_at_restart,
            "movesCompletedAfterRestart": st_b.metrics.meter(
                "rebalance.movesCompleted"
            ).count,
            "coverageNeverLost": coverage_never_lost,
            "rtRowsExpected": total_rt,
            "rtRowsServed": rt_counts,
            "oneCommittedCopyPerSequence": one_copy_per_seq,
            "finalLoadByServer": {k: round(v, 1) for k, v in sorted(balance.items())},
            "finalImbalanceRatio": round(final_ratio, 3),
            "skewRatioThreshold": st.rebalance_skew_ratio,
            "ingestPool": {"a": pool_a.snapshot(), "b": pool_b.snapshot()},
            "failedQueries": 0 if ok else max(1, failed),
        }
    finally:
        for load in loads:
            load.stop()
        pool_a.stop()
        if pool_b is not None:
            pool_b.stop()
        if ctrl_b is not None:
            ctrl_b.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# Network-partition scenarios (ISSUE 9): controller + servers + broker
# as real HTTP/TCP endpoints in ONE process, every link routed through a
# shared NetworkFaultInjector — the topology where "unreachable" and
# "dead" are different things.  Shared by the CLI and tests/test_partition.py.
# ---------------------------------------------------------------------------


class NetworkedCluster:
    """One-process networked cluster wired for link-level chaos.

    Unlike ``InProcessCluster`` (direct callbacks), every role here
    talks over its real protocol — servers/broker register, heartbeat,
    poll, and scatter over HTTP/TCP — and every link consults one
    seedable ``NetworkFaultInjector``, so a scenario can cut exactly
    the broker->controller poll or exactly the controller->server reply
    direction.  Timing knobs default tight so partition scenarios run
    at tier-1 speed."""

    def __init__(
        self,
        num_servers: int = 3,
        data_dir: Optional[str] = None,
        seed: int = 0,
        lease_s: float = 2.5,
        heartbeat_interval_s: float = 0.2,
        heartbeat_timeout_s: float = 1.2,
        poll_interval_s: float = 0.1,
    ) -> None:
        from pinot_tpu.broker.network_starter import NetworkedBrokerStarter
        from pinot_tpu.common.faults import NetworkFaultInjector
        from pinot_tpu.controller.controller import Controller, ControllerHttpServer
        from pinot_tpu.server.network_starter import NetworkedServerStarter

        self.data_dir = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_netchaos_")
        self.faults = NetworkFaultInjector(seed=seed)
        self.lease_s = lease_s
        # clients (starters + scatter transport) are injector-wired, so
        # the controller's gateway edge must NOT be: wiring both would
        # double-apply delay/error_rate/duplicate on controller links.
        # The gateway hook exists for harnesses that cannot reach the
        # client processes (OS-process chaos rigs).
        self.controller = Controller(self.data_dir, lease_s=lease_s)
        self.controller.gateway.heartbeat_timeout_s = heartbeat_timeout_s
        self.controller.gateway._check_interval_s = max(
            0.05, heartbeat_timeout_s / 4
        )
        self.http = ControllerHttpServer(self.controller)
        self.http.start()
        self.url = f"http://{self.http.host}:{self.http.port}"
        self.server_starters: List[NetworkedServerStarter] = []
        for i in range(num_servers):
            s = NetworkedServerStarter(
                self.url,
                f"srv{i}",
                data_dir=os.path.join(self.data_dir, f"cache{i}"),
                heartbeat_interval_s=heartbeat_interval_s,
                poll_interval_s=poll_interval_s,
                fault_injector=self.faults,
            )
            s.start()
            self.server_starters.append(s)
        self.broker_starter = NetworkedBrokerStarter(
            self.url,
            "brk0",
            heartbeat_interval_s=heartbeat_interval_s,
            poll_interval_s=poll_interval_s,
            fault_injector=self.faults,
        )
        self.broker_starter.start()

    @property
    def broker(self):
        """The broker request handler (ClosedLoopLoad compatibility)."""
        return self.broker_starter.handler

    def server(self, name: str):
        return next(s for s in self.server_starters if s.name == name)

    def query(self, pql: str) -> BrokerResponse:
        return self.broker.handle_pql(pql)

    def wait(self, cond, timeout_s: float = 25.0, what: str = "condition") -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if cond():
                    return
            except Exception:
                pass
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {what}")

    def stop(self) -> None:
        self.faults.heal()  # never leave stop() racing injected cuts
        self.broker_starter.stop()
        for s in self.server_starters:
            s.stop()
            s.server.shutdown()
        self.http.stop()
        self.controller.stop()


def _build_partition_cluster(
    num_servers: int = 3,
    replication: int = 2,
    num_segments: int = 6,
    data_dir: Optional[str] = None,
    seed: int = 5,
    **cluster_kwargs: Any,
):
    """Offline table over a NetworkedCluster, fully converged (every
    replica ONLINE, broker serving the complete count) before any
    weather is injected."""
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    cluster = NetworkedCluster(
        num_servers=num_servers, data_dir=data_dir, seed=seed, **cluster_kwargs
    )
    # grace zero: the LEASE window is the guard these scenarios test
    cluster.controller.stabilizer.grace_s = 0.0
    schema = make_test_schema(with_mv=False)
    cluster.controller.add_schema(schema)
    physical = cluster.controller.add_table(
        TableConfig(
            table_name="testTable", table_type="OFFLINE", replication=replication
        )
    )
    rows = random_rows(schema, 260, seed=seed)
    total = 0
    for i in range(num_segments):
        n = 30 + 45 * (i % 5)
        cluster.controller.upload_segment(
            physical, build_segment(schema, rows[:n], physical, f"seg{i}")
        )
        total += n

    res = cluster.controller.resources

    def converged():
        ideal = res.get_ideal_state(physical)
        view = res.get_external_view(physical)
        return (
            len(ideal) == num_segments
            and view == ideal
            and all(len(r) == replication for r in ideal.values())
            and all(
                st == "ONLINE" for r in view.values() for st in r.values()
            )
        )

    cluster.wait(converged, what="all replicas ONLINE")

    def serving():
        r = cluster.query("SELECT count(*) FROM testTable")
        return (
            r.num_docs_scanned == total
            and not r.exceptions
            and not r.partial_response
        )

    cluster.wait(serving, what="broker serving the full count")
    return cluster, physical, total


def run_partition_server_scenario(
    num_servers: int = 3,
    replication: int = 2,
    num_segments: int = 6,
    clients: int = 3,
    lease_s: float = 3.0,
    victim: str = "srv0",
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Sever one server's controller link (both directions) for longer
    than its lease under closed-loop load:

    - zero failed queries (the broker re-covers via replicas; the
      victim keeps answering in-flight work — it is alive, just
      unreachable from the controller);
    - its replicas move ONLY after the lease window (the stabilizer
      defers while the lease could still be live: leaseDeferrals > 0),
      never on the first missed heartbeat;
    - the victim self-fences (client-side lease expiry) and rides the
      outage visibly (controller.unreachable gauge);
    - on heal it rejoins cleanly: re-admitted, no duplicate replicas.
    """
    cluster, physical, total = _build_partition_cluster(
        num_servers, replication, num_segments, data_dir=data_dir,
        lease_s=lease_s,
    )
    res = cluster.controller.resources
    st = cluster.controller.stabilizer
    vsrv = cluster.server(victim).server
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.2)  # some queries complete pre-fault

        ideal_pre = res.get_ideal_state(physical)
        cluster.faults.partition(victim, "controller")
        cluster.wait(
            lambda: not res.instances[victim].alive,
            what="controller declaring the victim dead",
        )
        # single-missed-heartbeat point: dead at the gateway, but the
        # lease has NOT expired — a stabilizer round must move NOTHING
        # (the ideal state stays byte-identical, not merely "victim
        # still holds something": a drop+replace in one round would
        # otherwise pass)
        st.run_once()
        ideal_mid = res.get_ideal_state(physical)
        held_through_lease = ideal_mid == ideal_pre
        moved_on_heartbeat = ideal_mid != ideal_pre
        lease_deferrals = st.metrics.meter("stabilizer.leaseDeferrals").count

        # the victim notices on its side: lease expires, gauge flips
        cluster.wait(lambda: not vsrv.lease.held(), what="victim lease expiry")
        cluster.wait(
            lambda: vsrv.metrics.gauge("controller.unreachable").value == 1,
            what="victim unreachable gauge",
        )
        # controller side: wait out the lease window, then re-replicate
        cluster.wait(
            lambda: res.instances[victim].lease_until is not None
            and time.monotonic() >= res.instances[victim].lease_until,
            what="lease window elapsing",
        )
        for _ in range(4):
            st.run_once()
            time.sleep(0.1)
        cluster.wait(
            lambda: not any(
                victim in r
                for r in res.get_ideal_state(physical).values()
            ),
            what="victim replicas dropped after lease expiry",
        )
        cluster.wait(
            lambda: res.get_external_view(physical)
            == res.get_ideal_state(physical)
            and all(
                len(r) == min(replication, num_servers - 1)
                for r in res.get_ideal_state(physical).values()
            ),
            what="re-replication converged",
        )

        # heal: the victim rejoins cleanly
        cluster.faults.heal()
        cluster.wait(
            lambda: res.instances[victim].alive, what="victim re-admitted"
        )
        cluster.wait(lambda: vsrv.lease.held(), what="victim lease renewed")
        st.run_once()
        time.sleep(0.2)
        summary = load.stop()

        ideal = res.get_ideal_state(physical)
        final = cluster.query("SELECT count(*) FROM testTable")
        no_duplicates = all(len(r) <= replication for r in ideal.values())
        return {
            "scenario": "partition-server",
            "victim": victim,
            "leaseSeconds": lease_s,
            **summary,
            "heldThroughLeaseWindow": held_through_lease,
            "movedOnFirstMissedHeartbeat": moved_on_heartbeat,
            "leaseDeferrals": lease_deferrals,
            "victimSelfFenced": True,  # waited on lease.held() == False
            "replicationRestored": all(
                len(r) == min(replication, num_servers - 1)
                for r in ideal.values()
            )
            or all(len(r) == replication for r in ideal.values()),
            "noDuplicateReplicas": no_duplicates,
            "victimReadmitted": res.instances[victim].alive,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
            "stabilizer": st.metrics.snapshot()["meters"],
        }
    finally:
        cluster.stop()


def run_partition_controller_scenario(
    num_servers: int = 2,
    replication: int = 2,
    num_segments: int = 4,
    clients: int = 3,
    lease_s: float = 1.2,
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Sever the controller from EVERY other role: the whole data plane
    rides out the control-plane outage — the broker serves from its
    last versioned snapshot (controller.unreachable=1), servers
    self-fence writes but keep answering queries, the stabilizer moves
    NOTHING (no live target exists), and on heal everyone re-admits
    with the ideal state byte-identical to before the outage."""
    cluster, physical, total = _build_partition_cluster(
        num_servers, replication, num_segments, data_dir=data_dir,
        lease_s=lease_s,
    )
    res = cluster.controller.resources
    st = cluster.controller.stabilizer
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.2)
        ideal_before = res.get_ideal_state(physical)

        # cut the BROKER first: its last-applied snapshot must be the
        # healthy one (a poll racing the server cuts could otherwise
        # deliver a snapshot that already lists the servers dead)
        cluster.faults.partition("brk0", "controller")
        time.sleep(0.15)
        for s in cluster.server_starters:
            cluster.faults.partition(s.name, "controller")

        cluster.wait(
            lambda: all(
                not res.instances[s.name].alive
                for s in cluster.server_starters
            ),
            what="controller declaring every server dead",
        )
        cluster.wait(
            lambda: cluster.broker.metrics.gauge("controller.unreachable").value
            == 1,
            what="broker unreachable gauge",
        )
        cluster.wait(
            lambda: all(
                not s.server.lease.held() for s in cluster.server_starters
            ),
            what="server leases expiring",
        )
        # stabilizer rounds during the outage: nowhere to move anything
        for _ in range(3):
            st.run_once()
        unchanged_during = res.get_ideal_state(physical) == ideal_before

        cluster.faults.heal()
        cluster.wait(
            lambda: all(
                res.instances[s.name].alive for s in cluster.server_starters
            ),
            what="servers re-admitted",
        )
        cluster.wait(
            lambda: cluster.broker.metrics.gauge("controller.unreachable").value
            == 0,
            what="broker poll recovery",
        )
        cluster.wait(
            lambda: all(
                s.server.lease.held() for s in cluster.server_starters
            ),
            what="leases renewed",
        )
        # recovery is CONVERGED (not just re-admitted) once every
        # replica's ONLINE re-ack has landed: bounded unavailability
        # ends here, and the final query must be complete
        cluster.wait(
            lambda: res.get_external_view(physical)
            == res.get_ideal_state(physical),
            what="external view reconverged after heal",
        )
        st.run_once()
        # ... and the broker has applied it (one poll cycle): bounded
        # by the wait timeout, which IS the unavailability bound
        cluster.wait(
            lambda: (
                lambda r: r.num_docs_scanned == total
                and not r.partial_response
                and not r.exceptions
            )(cluster.query("SELECT count(*) FROM testTable")),
            what="broker serving the full count after heal",
        )
        summary = load.stop()
        final = cluster.query("SELECT count(*) FROM testTable")
        return {
            "scenario": "partition-controller",
            "leaseSeconds": lease_s,
            **summary,
            "idealUnchangedDuringOutage": unchanged_during,
            "idealUnchangedAfterHeal": res.get_ideal_state(physical)
            == ideal_before,
            "brokerServedFromSnapshot": True,  # waited on the gauge flip
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
        }
    finally:
        cluster.stop()


def run_asymmetric_partition_scenario(
    data_dir: Optional[str] = None,
    lease_s: float = 1.2,
    rows_initial: int = 40,
    rows_appended: int = 30,
    rows_per_segment: int = 30,
    victim: str = "srv0",
) -> Dict[str, Any]:
    """One-way partition on the REALTIME commit plane: the victim's
    requests reach the controller (it keeps looking alive — heartbeats
    arrive) but every reply is lost, so only the victim knows it is
    partitioned.  Its client-side lease expires and self-fences write
    authority: completion rounds freeze with offsets intact, no
    replica moves (the controller sees a healthy server), reads keep
    serving, and the OTHER replica is elected committer after the hold
    window — exactly one committed segment, nothing lost or doubled.
    On heal the victim renews, downloads the committed copy
    (byte-identical CRC), and the lagging partition catches up."""
    import json as _json

    from pinot_tpu.common.schema import (
        DataType,
        FieldSpec,
        FieldType,
        Schema,
        TimeFieldSpec,
    )
    from pinot_tpu.common.tableconfig import StreamConfig
    from pinot_tpu.realtime.llc import make_segment_name
    from pinot_tpu.realtime.stream import FileBasedStreamProvider

    cluster = NetworkedCluster(
        num_servers=2, data_dir=data_dir, lease_s=lease_s
    )
    cluster.controller.stabilizer.grace_s = 0.0
    res = cluster.controller.resources
    st = cluster.controller.stabilizer
    try:
        schema = Schema(
            "rsvpNet",
            dimensions=[FieldSpec("venue", DataType.STRING)],
            metrics=[FieldSpec("rsvps", DataType.INT, FieldType.METRIC)],
            time_field=TimeFieldSpec(
                "mtime", DataType.LONG, time_unit="MILLISECONDS"
            ),
        )

        def _row(i: int) -> Dict[str, Any]:
            return {"venue": f"v{i % 3}", "rsvps": i % 5, "mtime": 10_000 + i}

        stream_path = os.path.join(cluster.data_dir, "stream_p0.jsonl")
        with open(stream_path, "w") as f:
            for i in range(rows_initial):
                f.write(_json.dumps(_row(i)) + "\n")

        cluster.controller.add_schema(schema)
        config = TableConfig(
            table_name="rsvpNet",
            table_type="REALTIME",
            replication=2,
            stream=StreamConfig(
                stream_type="file", rows_per_segment=rows_per_segment,
                properties={"paths": [stream_path]},
            ),
        )
        physical = cluster.controller.add_realtime_table(
            config, FileBasedStreamProvider([stream_path])
        )

        def count() -> int:
            r = cluster.query("SELECT count(*) FROM rsvpNet")
            return -1 if r.exceptions else r.num_docs_scanned

        # first segment commits (both replicas reachable), remainder
        # consumes into the next sequence
        seg0 = make_segment_name(physical, 0, 0)
        cluster.wait(
            lambda: res.get_ideal_state(physical).get(seg0, {})
            and all(
                stt == "ONLINE"
                for stt in res.get_ideal_state(physical)[seg0].values()
            ),
            what="first segment committed",
        )
        cluster.wait(
            lambda: count() == rows_initial, what="all initial rows served"
        )

        # one-way cut: victim -> controller REQUESTS still flow, every
        # controller -> victim REPLY is lost
        cluster.faults.cut("controller", victim)
        vsrv = cluster.server(victim).server
        cluster.wait(
            lambda: not vsrv.lease.held(), what="victim lease self-fencing"
        )
        blocked_before = vsrv.metrics.meter("lease.blockedCommits").count

        # next threshold arrives mid-partition: only the healthy
        # replica can run the completion protocol
        with open(stream_path, "a") as f:
            for i in range(rows_initial, rows_initial + rows_appended):
                f.write(_json.dumps(_row(i)) + "\n")

        seg1 = make_segment_name(physical, 0, 1)
        cluster.wait(
            lambda: res.get_ideal_state(physical).get(seg1, {})
            and any(
                stt == "ONLINE"
                for stt in res.get_ideal_state(physical)[seg1].values()
            ),
            timeout_s=30.0,
            what="mid-partition commit by the healthy replica",
        )
        st.run_once()
        controller_saw_alive = res.instances[victim].alive
        no_movement = st.metrics.meter("stabilizer.replicasAdded").count == 0
        blocked_commits = (
            vsrv.metrics.meter("lease.blockedCommits").count > blocked_before
        )
        total = rows_initial + rows_appended
        served_during = count()

        # heal: victim renews, downloads the committed copy, catches up
        cluster.faults.heal()
        cluster.wait(lambda: vsrv.lease.held(), what="victim lease renewal")
        cluster.wait(
            lambda: res.get_external_view(physical).get(seg1, {}).get(victim)
            == "ONLINE",
            timeout_s=30.0,
            what="victim downloading the committed copy",
        )
        cluster.wait(lambda: count() == total, what="full count after heal")

        # byte-identity: both replicas loaded the same committed bytes
        crcs = []
        for s in cluster.server_starters:
            tdm = s.server.data_manager.table(physical)
            acquired = tdm.acquire_segments([seg1])
            try:
                crcs.extend(d.segment.metadata.crc for d in acquired)
            finally:
                tdm.release_segments(acquired)
        byte_identical = len(crcs) == 2 and len(set(crcs)) == 1

        final = cluster.query("SELECT count(*) FROM rsvpNet")
        ok = (
            final.num_docs_scanned == total
            and not final.exceptions
            and blocked_commits
            and controller_saw_alive
            and no_movement
            and byte_identical
        )
        return {
            "scenario": "asymmetric-partition",
            "victim": victim,
            "leaseSeconds": lease_s,
            "victimSelfFenced": blocked_commits,
            "controllerSawVictimAlive": controller_saw_alive,
            "noReplicaMovement": no_movement,
            "servedDuringPartition": served_during,
            "committedByteIdentical": byte_identical,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "failedQueries": 0 if ok else 1,
        }
    finally:
        cluster.stop()


def run_split_brain_scenario(data_dir: Optional[str] = None) -> Dict[str, Any]:
    """Two controllers over one property store: A builds the cluster,
    then B claims the store (epoch+1) — A is now a zombie.  EVERY write
    A attempts (drain, quota, upload, delete, stabilizer round) raises
    a typed StaleEpochError and mutates nothing durable; commit-plane
    calls carrying the wrong incarnation's lease epoch are rejected in
    BOTH directions; and the ideal state converges to B's fixpoint."""
    from pinot_tpu.common.fencing import StaleEpochError
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.server.starter import ServerStarter
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    data_dir = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_splitbrain_")
    cluster_a = InProcessCluster(num_servers=2, data_dir=data_dir)
    ctrl_a = cluster_a.controller
    schema = make_test_schema(with_mv=False)
    physical = cluster_a.add_offline_table(schema, replication=2)
    rows = random_rows(schema, 120, seed=11)
    total = 0
    for i in range(3):
        n = 30 + 10 * i
        cluster_a.upload(physical, build_segment(schema, rows[:n], physical, f"sb{i}"))
        total += n
    ideal_a = ctrl_a.resources.get_ideal_state(physical)

    # B claims the store: A is fenced from this moment
    ctrl_b = Controller(data_dir)
    ctrl_b.stabilizer.grace_s = 0.0
    servers_b = {}
    for name in ("server0", "server1"):
        s = ServerInstance(name)
        ServerStarter(s, ctrl_b.resources).start()
        servers_b[name] = s

    stale_rejections: Dict[str, bool] = {}

    def _stale(label: str, fn) -> None:
        try:
            fn()
            stale_rejections[label] = False
        except StaleEpochError:
            stale_rejections[label] = True
        except Exception:
            stale_rejections[label] = False

    try:
        store_ideal_before = ctrl_b.property_store.get("idealstates", physical)
        # stabilizer first: later attempts corrupt the zombie's own
        # memory (fenced writes fail AFTER their in-memory mutation),
        # which could leave it nothing live to re-replicate onto
        _stale("stabilizerWrite", lambda: _zombie_stabilizer_write(ctrl_a, physical))
        _stale(
            "upload",
            lambda: ctrl_a.upload_segment(
                physical, build_segment(schema, rows[:20], physical, "zombie")
            ),
        )
        _stale(
            "quota",
            lambda: ctrl_a.resources.update_table_quota(physical, 5.0),
        )
        _stale("delete", lambda: ctrl_a.delete_segment(physical, "sb0"))
        _stale("drain", lambda: ctrl_a.drain_instance("server0"))
        # commit plane, both directions: B's epoch at A, A's epoch at B
        _stale(
            "commitPlaneAtZombie",
            lambda: ctrl_a.realtime_manager.completion.segment_consumed(
                f"{physical}__0__0", "server0", 10, epoch=ctrl_b.epoch
            ),
        )
        _stale(
            "commitPlaneAtLive",
            lambda: ctrl_b.realtime_manager.completion.segment_consumed(
                f"{physical}__0__0", "server0", 10, epoch=ctrl_a.epoch
            ),
        )
        store_ideal_after = ctrl_b.property_store.get("idealstates", physical)
        store_unchanged = store_ideal_before == store_ideal_after

        # the live controller converges to ITS fixpoint (kill a server
        # to force real stabilizer work post-fence)
        ctrl_b.resources.set_instance_alive("server0", False)
        for _ in range(3):
            ctrl_b.stabilizer.run_once()
        ideal_b = ctrl_b.resources.get_ideal_state(physical)
        converged = (
            all("server0" not in r for r in ideal_b.values())
            and all(len(r) == 1 for r in ideal_b.values())
            and ctrl_b.resources.get_external_view(physical) == ideal_b
        )
        # idempotent: one more round changes nothing
        ctrl_b.stabilizer.run_once()
        converged = converged and ctrl_b.resources.get_ideal_state(physical) == ideal_b

        all_rejected = all(stale_rejections.values())
        return {
            "scenario": "split-brain",
            "epochA": ctrl_a.epoch,
            "epochB": ctrl_b.epoch,
            "staleRejections": stale_rejections,
            "allStaleWritesRejected": all_rejected,
            "durableStoreUnchangedByZombie": store_unchanged,
            "liveControllerConverged": converged,
            "staleEpochRejectionsMetered": ctrl_a.metrics.meter(
                "fence.staleEpochRejections"
            ).count
            + ctrl_b.metrics.meter("fence.staleEpochRejections").count,
            "failedQueries": 0
            if (all_rejected and store_unchanged and converged)
            else 1,
        }
    finally:
        ctrl_b.stop()
        cluster_a.stop()


def _zombie_stabilizer_write(ctrl_a, physical: str) -> None:
    """Force the zombie's stabilizer to attempt a persisted write (its
    own view says a server died); must raise StaleEpochError."""
    ctrl_a.resources.set_instance_alive("server1", False)
    ctrl_a.stabilizer.grace_s = 0.0
    before = ctrl_a.resources.get_ideal_state(physical)
    ctrl_a.stabilizer.run_once()
    # a fenced run_once swallows nothing: add_segment_replica raises
    # through run_once — if we got here, no exception fired, so check
    # whether anything was durably persisted (it must not have been)
    after = ctrl_a.resources.get_ideal_state(physical)
    if before == after:
        raise RuntimeError("stabilizer made no write attempt (test rig issue)")


# ---------------------------------------------------------------------------
# Disaster-recovery scenario (ISSUE 20): consistent online backup under
# closed-loop load, a seeded deep-store corruption scrubbed + repaired
# from a live replica, then the controller property store DESTROYED
# mid-load and the cluster restored from archive + deep store alone —
# byte-identical answers, zero committed-row loss, drain flags and
# epoch fencing preserved.  Shared by the CLI, DR_r20.json generation,
# and tests/test_disaster_recovery.py.
# ---------------------------------------------------------------------------


def run_disaster_recovery_scenario(
    num_servers: int = 3,
    replication: int = 2,
    num_segments: int = 6,
    clients: int = 3,
    rt_rows_per_segment: int = 40,
    window_s: float = 0.5,
    data_dir: Optional[str] = None,
    archive_path: Optional[str] = None,
    seed: int = 2020,
) -> Dict[str, Any]:
    import json
    import shutil as _shutil

    from pinot_tpu.common.fencing import StaleEpochError
    from pinot_tpu.common.tableconfig import StreamConfig
    from pinot_tpu.realtime.llc import RESP_KEEP, make_segment_name
    from pinot_tpu.realtime.stream import FileBasedStreamProvider
    from pinot_tpu.tools.backup import create_backup, restore_backup
    from pinot_tpu.tools.datagen import random_rows
    from pinot_tpu.utils.audit import SamplerBudget, strip_accounting

    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir, seed=seed
    )
    old_ctrl = cluster.controller
    archive = archive_path or os.path.join(cluster.data_dir, "dr_backup.tar.gz")
    try:
        # -- 1. drain one server (the flag must survive the disaster) --
        drained = "server2" if num_servers >= 3 else None
        if drained:
            _drain_one(cluster, drained)

        # -- 2. realtime table: commit two segments' worth of rows -----
        rt_schema = _tenant_schema("rtTable")
        stream_file = os.path.join(cluster.data_dir, "rt_p0.jsonl")
        rt_rows = random_rows(rt_schema, rt_rows_per_segment * 3, seed=seed + 1)
        with open(stream_file, "w") as f:
            for r in rt_rows[: rt_rows_per_segment * 2]:
                f.write(json.dumps(r) + "\n")
        cluster.controller.add_schema(rt_schema)
        rt_config = TableConfig(
            table_name="rtTable",
            table_type="REALTIME",
            replication=1,
            stream=StreamConfig(rows_per_segment=rt_rows_per_segment),
        )
        rt_physical = cluster.controller.add_realtime_table(
            rt_config, FileBasedStreamProvider([stream_file])
        )
        rt_seg = [make_segment_name(rt_physical, 0, i) for i in range(3)]
        dm0 = cluster.controller.realtime_manager.consumers_of(rt_seg[0])[0]
        dm0.consume_step(max_rows=100_000)
        assert dm0.try_commit() == RESP_KEEP
        dm1 = cluster.controller.realtime_manager.consumers_of(rt_seg[1])[0]
        dm1.consume_step(max_rows=100_000)
        assert dm1.try_commit() == RESP_KEEP
        rt_committed = rt_rows_per_segment * 2
        rt_pql = "SELECT count(*) FROM rtTable"
        assert cluster.query(rt_pql).num_docs_scanned == rt_committed

        # -- 3. canonical pre-disaster payloads (byte-identity bar) ----
        canon = [
            "SELECT count(*) FROM testTable",
            "SELECT sum(metInt), max(dimInt) FROM testTable GROUP BY dimStr",
            rt_pql,
        ]
        baseline_payloads = {}
        for q in canon:
            resp = cluster.query(q)
            assert not resp.exceptions and not resp.partial_response, q
            baseline_payloads[q] = strip_accounting(resp.to_json())

        # -- 4. closed-loop load for the rest of the scenario ----------
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        t0 = time.monotonic()
        time.sleep(window_s)
        ok0, tA = load.ok, time.monotonic()
        baseline_qps = ok0 / max(1e-6, tA - t0)

        # -- 5. consistent online backup (timed, under load) -----------
        backup_stats = create_backup(cluster.data_dir, archive)

        # -- 6. seed deep-store corruption; scrub detects + repairs ----
        store = cluster.controller.store
        victim_seg = "seg0"
        victim_path = store.segment_file_path(physical, victim_seg)
        with open(victim_path, "r+b") as f:
            f.seek(-16, os.SEEK_END)
            f.write(b"\xde\xad\xbe\xef" * 4)

        def in_process_copy(name, url, table, segment):
            for s in cluster.servers:
                if s.name == name:
                    return s.segment_copy_bytes(table, segment)
            return None

        scrub = cluster.controller.deepstore_scrubber
        scrub.copy_fn = in_process_copy
        scrub.budget = SamplerBudget(per_s=100_000.0, burst=10_000.0)
        scrub_t0 = time.monotonic()
        okA = load.ok
        scrub.run_once()
        time.sleep(window_s)  # serving window with the scrub round in it
        scrub_t1, okB = time.monotonic(), load.ok
        scrub_qps = (okB - okA) / max(1e-6, scrub_t1 - scrub_t0)
        scrub_snap = scrub.snapshot()
        scrub_repaired = False
        try:
            info = cluster.controller.resources.get_segment_metadata(
                physical, victim_seg
            ) or {}
            store.verify_copy(
                physical, victim_seg,
                expected_crc=getattr(info.get("metadata"), "crc", None),
            )
            scrub_repaired = True
        except Exception:
            pass
        ok_qps_ratio = min(1.0, scrub_qps / max(1e-6, baseline_qps))

        # -- 7. DISASTER: property store destroyed mid-load ------------
        _shutil.rmtree(os.path.join(cluster.data_dir, "property_store"))
        time.sleep(0.2)  # queries keep flowing: broker routing survives
        old_ctrl.stop()

        # -- 8. restore: new controller from archive + deep store ------
        restore_t0 = time.monotonic()
        restore_stats = restore_backup(archive, cluster.data_dir)
        new_ctrl = Controller(cluster.data_dir)
        new_ctrl.stabilizer.grace_s = 0.0
        cluster.controller = new_ctrl
        # servers first (replays refill the external views), then the
        # broker (re-seeds routing from those views) — the elastic-fleet
        # in-process restart pattern
        for server in cluster.servers:
            ServerStarter(server, new_ctrl.resources).start()
        BrokerStarter(cluster.broker, new_ctrl.resources).start()
        first_query_s = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            resp = cluster.query("SELECT count(*) FROM testTable")
            if (
                not resp.exceptions
                and not resp.partial_response
                and resp.num_docs_scanned == total
            ):
                first_query_s = time.monotonic() - restore_t0
                break
            time.sleep(0.05)
        time.sleep(window_s)  # post-restore serving window under load
        summary = load.stop()

        # -- 9. acceptance accounting ----------------------------------
        byte_identical = True
        for q in canon:
            resp = cluster.query(q)
            if (
                resp.exceptions
                or resp.partial_response
                or strip_accounting(resp.to_json()) != baseline_payloads[q]
            ):
                byte_identical = False
        drain_preserved = (
            drained is None
            or drained in new_ctrl.resources._draining_flags
        )
        # fencing: the pre-disaster zombie's writes must still be
        # rejected against the restored store
        try:
            old_ctrl.property_store.put("tables", "zombieWrite", {"x": 1})
            fencing_preserved = False
        except StaleEpochError:
            fencing_preserved = True
        # realtime: committed rows exactly once, consumption resumes
        rt_after = cluster.query(rt_pql).num_docs_scanned
        rt_committed_preserved = rt_after == rt_committed
        rt_resumed = False
        try:
            with open(stream_file, "a") as f:
                for r in rt_rows[rt_rows_per_segment * 2 :]:
                    f.write(json.dumps(r) + "\n")
            dm2 = new_ctrl.realtime_manager.consumers_of(rt_seg[2])[0]
            dm2.consume_step(max_rows=100_000)
            rt_resumed = (
                dm2.try_commit() == RESP_KEEP
                and cluster.query(rt_pql).num_docs_scanned
                == rt_rows_per_segment * 3
            )
        except Exception:
            rt_resumed = False

        scrub_detected = scrub_snap["corruptCopies"] >= 1
        failed = (
            summary["failedQueries"]
            + (0 if first_query_s is not None else 1)
            + (0 if byte_identical else 1)
            + (0 if drain_preserved else 1)
            + (0 if fencing_preserved else 1)
            + (0 if rt_committed_preserved else 1)
            + (0 if rt_resumed else 1)
            + (0 if (scrub_detected and scrub_repaired) else 1)
        )
        return {
            "scenario": "disaster-recovery",
            "metric": "dr_restore_first_query_s",
            "platform": "cpu",
            "num_segments": num_segments,
            "clients": clients,
            "value": round(first_query_s, 4) if first_query_s else None,
            "backup": backup_stats,
            "restore": {
                "restoreToFirstQuerySeconds": (
                    round(first_query_s, 4) if first_query_s else None
                ),
                "restoreSeconds": round(restore_stats["restoreSeconds"], 4),
                "segmentsVerified": restore_stats["segmentsVerified"],
                "segmentsMissing": restore_stats["segmentsMissing"],
                "segmentsCorrupt": restore_stats["segmentsCorrupt"],
                "byteIdentical": byte_identical,
                "drainFlagPreserved": drain_preserved,
                "fencingPreserved": fencing_preserved,
                "rtCommittedPreserved": rt_committed_preserved,
                "rtResumed": rt_resumed,
            },
            "scrub": {
                "detected": scrub_detected,
                "repaired": scrub_repaired,
                "okQpsRatio": round(ok_qps_ratio, 4),
                "baselineQps": round(baseline_qps, 2),
                "scrubQps": round(scrub_qps, 2),
                "snapshot": scrub_snap,
            },
            "load": summary,
            "failedQueries": failed,
        }
    finally:
        cluster.stop()


SCENARIOS = {
    "kill-server": run_kill_server_scenario,
    "drain": run_drain_scenario,
    "rolling-restart": run_rolling_restart_scenario,
    "rolling-restart-warm": run_rolling_restart_warm_scenario,
    "elastic-fleet": run_elastic_fleet_scenario,
    "noisy-neighbor": run_noisy_neighbor_scenario,
    "join-under-flood": run_join_under_flood_scenario,
    "ingest-backpressure": run_ingest_backpressure_scenario,
    "hbm-pressure": run_hbm_pressure_scenario,
    "audit-divergence": run_audit_divergence_scenario,
    "partition-server": run_partition_server_scenario,
    "partition-controller": run_partition_controller_scenario,
    "asymmetric-partition": run_asymmetric_partition_scenario,
    "split-brain": run_split_brain_scenario,
    "disaster-recovery": run_disaster_recovery_scenario,
}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", choices=sorted(SCENARIOS), required=True)
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--replication", type=int, default=2)
    p.add_argument("--segments", type=int, default=6)
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--quota-qps", type=float, default=8.0)
    p.add_argument("--flood-clients", type=int, default=4)
    p.add_argument("--tables", type=int, default=104)
    args = p.parse_args(argv)
    if args.scenario == "elastic-fleet":
        out = run_elastic_fleet_scenario(
            num_tables=args.tables,
            num_servers=args.servers,
            clients=args.clients,
        )
        import json as _json

        print(_json.dumps(out, indent=2))
        return 0 if out["failedQueries"] == 0 else 1
    if args.scenario in (
        "ingest-backpressure",
        "hbm-pressure",
        "audit-divergence",
        "asymmetric-partition",
        "split-brain",
    ):
        out = SCENARIOS[args.scenario]()
    elif args.scenario == "partition-server":
        out = SCENARIOS[args.scenario](
            num_servers=args.servers,
            replication=args.replication,
            num_segments=args.segments,
            clients=args.clients,
        )
    elif args.scenario == "partition-controller":
        out = SCENARIOS[args.scenario](
            num_servers=min(args.servers, 3),
            replication=args.replication,
            num_segments=min(args.segments, 4),
            clients=args.clients,
        )
    elif args.scenario == "rolling-restart-warm":
        # sequential replay (clients=1): the compile.cold == 0 bar is
        # deterministic only when no novel micro-batched combo shape
        # can appear for the first time mid-roll
        out = SCENARIOS[args.scenario](
            num_servers=args.servers,
            replication=args.replication,
            num_segments=args.segments,
        )
    elif args.scenario == "noisy-neighbor":
        out = SCENARIOS[args.scenario](
            num_servers=min(args.servers, 2),
            replication=args.replication,
            num_segments=args.segments,
            clients=args.clients,
            flood_clients=args.flood_clients,
            quota_qps=args.quota_qps,
        )
    else:
        out = SCENARIOS[args.scenario](
            num_servers=args.servers,
            replication=args.replication,
            num_segments=args.segments,
            clients=args.clients,
        )
    print(json.dumps(out, indent=2))
    return 0 if out["failedQueries"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
