"""In-process cluster harness: controller + N servers + broker in one
process.

The reference's ``PerfBenchmarkDriver.java:61`` (starts the whole
cluster in-process, :160-162) and the integration tests' ``ClusterTest``
use the same trick; this is the standard harness for quickstarts, perf
runs, and integration tests.
"""
from __future__ import annotations

import tempfile
from typing import Dict, List, Optional, Sequence

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.broker.starter import BrokerStarter
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.starter import ServerStarter
from pinot_tpu.transport.local import LocalTransport


class InProcessCluster:
    def __init__(
        self,
        num_servers: int = 2,
        data_dir: Optional[str] = None,
        mesh=None,
        http: bool = False,
        timeout_ms: float = 15_000.0,
    ) -> None:
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_cluster_")
        self.controller = Controller(self.data_dir)
        self.transport = LocalTransport()

        self.servers: List[ServerInstance] = []
        self.server_starters: List[ServerStarter] = []
        addresses: Dict[str, tuple] = {}
        for i in range(num_servers):
            server = ServerInstance(f"server{i}", mesh=mesh)
            starter = ServerStarter(server, self.controller.resources)
            starter.start()
            address = (server.name, 0)
            self.transport.register(address, server.handle_request)
            addresses[server.name] = address
            self.servers.append(server)
            self.server_starters.append(starter)

        self.broker = BrokerRequestHandler(
            self.transport, addresses, name="broker0", timeout_ms=timeout_ms
        )
        self.http: Optional[BrokerHttpServer] = None
        broker_url = None
        if http:
            self.http = BrokerHttpServer(self.broker)
            self.http.start()
            broker_url = f"http://{self.http.host}:{self.http.port}"
        self.broker_starter = BrokerStarter(
            self.broker, self.controller.resources, url=broker_url
        )
        self.broker_starter.start()

    def add_server(self, name: Optional[str] = None, mesh=None) -> ServerInstance:
        """Join a new server into the running cluster (elastic scale-out;
        pair with controller.rebalance_table to move segments onto it)."""
        name = name or f"server{len(self.servers)}"
        server = ServerInstance(name, mesh=mesh)
        starter = ServerStarter(server, self.controller.resources)
        starter.start()
        address = (server.name, 0)
        self.transport.register(address, server.handle_request)
        self.broker.set_server_address(server.name, address)
        self.servers.append(server)
        self.server_starters.append(starter)
        return server

    # -- convenience API ---------------------------------------------
    def add_offline_table(
        self, schema: Schema, table_name: Optional[str] = None, **config_kwargs
    ) -> str:
        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name, table_type="OFFLINE", **config_kwargs
        )
        return self.controller.add_table(config)

    def add_realtime_table(
        self,
        schema: Schema,
        stream,
        table_name: Optional[str] = None,
        rows_per_segment: int = 1000,
        replication: int = 1,
    ) -> str:
        from pinot_tpu.common.tableconfig import StreamConfig

        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name,
            table_type="REALTIME",
            replication=replication,
            stream=StreamConfig(stream_type="memory", rows_per_segment=rows_per_segment),
        )
        return self.controller.add_realtime_table(config, stream)

    def upload(self, physical_table: str, segment: ImmutableSegment) -> None:
        self.controller.upload_segment(physical_table, segment)

    def query(self, pql: str, trace: bool = False) -> BrokerResponse:
        return self.broker.handle_pql(pql, trace=trace)

    def stop(self) -> None:
        if self.http is not None:
            self.http.stop()
        self.controller.stop()


def single_server_broker(
    table: str,
    segments,
    timeout_ms: float = 600_000.0,
    max_pending: int = 64,
    **server_kwargs,
):
    """One in-process server + broker over LocalTransport — the
    minimal serving topology every bench uses (bench.py,
    tools/config_bench.py).  The generous default timeout covers the
    first query's staging + compile on a tunneled chip.  Extra kwargs
    reach the ServerInstance (e.g. ``pipeline=False`` for the serial
    executor path); the instance is reachable as
    ``broker.local_servers[0]`` so benches can read lane/scheduler
    counters."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.routing import RoutingTableProvider

    server = ServerInstance("benchServer", max_pending=max_pending, **server_kwargs)
    for seg in segments:
        server.add_segment(table, seg)
    transport = LocalTransport()
    transport.register(("benchServer", 0), server.handle_request)
    routing = RoutingTableProvider()
    routing.update(table, {s.segment_name: {"benchServer": "ONLINE"} for s in segments})
    broker = BrokerRequestHandler(
        transport,
        {"benchServer": ("benchServer", 0)},
        routing=routing,
        timeout_ms=timeout_ms,
    )
    broker.local_servers = [server]
    return broker
