"""In-process cluster harness: controller + N servers + broker in one
process.

The reference's ``PerfBenchmarkDriver.java:61`` (starts the whole
cluster in-process, :160-162) and the integration tests' ``ClusterTest``
use the same trick; this is the standard harness for quickstarts, perf
runs, and integration tests.

``--scenario kill-server|drain|rolling-restart`` runs the cluster
self-stabilization chaos scenarios (closed-loop query load while a
server dies / drains / every server rolls): the SAME scenario code
drives manual chaos runs from this CLI and the deterministic tier-1
chaos tests (``tests/test_stabilizer.py``).
"""
from __future__ import annotations

import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.broker.starter import BrokerStarter
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.starter import ServerStarter
from pinot_tpu.transport.local import LocalTransport


class InProcessCluster:
    def __init__(
        self,
        num_servers: int = 2,
        data_dir: Optional[str] = None,
        mesh=None,
        http: bool = False,
        timeout_ms: float = 15_000.0,
    ) -> None:
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_cluster_")
        self.controller = Controller(self.data_dir)
        self.transport = LocalTransport()

        self.servers: List[ServerInstance] = []
        self.server_starters: List[ServerStarter] = []
        addresses: Dict[str, tuple] = {}
        for i in range(num_servers):
            server = ServerInstance(f"server{i}", mesh=mesh)
            starter = ServerStarter(server, self.controller.resources)
            starter.start()
            address = (server.name, 0)
            self.transport.register(address, server.handle_request)
            addresses[server.name] = address
            self.servers.append(server)
            self.server_starters.append(starter)

        self.broker = BrokerRequestHandler(
            self.transport, addresses, name="broker0", timeout_ms=timeout_ms
        )
        self.http: Optional[BrokerHttpServer] = None
        broker_url = None
        if http:
            self.http = BrokerHttpServer(self.broker)
            self.http.start()
            broker_url = f"http://{self.http.host}:{self.http.port}"
        self.broker_starter = BrokerStarter(
            self.broker, self.controller.resources, url=broker_url
        )
        self.broker_starter.start()

    def add_server(self, name: Optional[str] = None, mesh=None) -> ServerInstance:
        """Join a new server into the running cluster (elastic scale-out;
        pair with controller.rebalance_table to move segments onto it)."""
        name = name or f"server{len(self.servers)}"
        server = ServerInstance(name, mesh=mesh)
        starter = ServerStarter(server, self.controller.resources)
        starter.start()
        address = (server.name, 0)
        self.transport.register(address, server.handle_request)
        self.broker.set_server_address(server.name, address)
        self.servers.append(server)
        self.server_starters.append(starter)
        return server

    # -- convenience API ---------------------------------------------
    def add_offline_table(
        self, schema: Schema, table_name: Optional[str] = None, **config_kwargs
    ) -> str:
        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name, table_type="OFFLINE", **config_kwargs
        )
        return self.controller.add_table(config)

    def add_realtime_table(
        self,
        schema: Schema,
        stream,
        table_name: Optional[str] = None,
        rows_per_segment: int = 1000,
        replication: int = 1,
    ) -> str:
        from pinot_tpu.common.tableconfig import StreamConfig

        self.controller.add_schema(schema)
        config = TableConfig(
            table_name=table_name or schema.schema_name,
            table_type="REALTIME",
            replication=replication,
            stream=StreamConfig(stream_type="memory", rows_per_segment=rows_per_segment),
        )
        return self.controller.add_realtime_table(config, stream)

    def upload(self, physical_table: str, segment: ImmutableSegment) -> None:
        self.controller.upload_segment(physical_table, segment)

    def query(self, pql: str, trace: bool = False) -> BrokerResponse:
        return self.broker.handle_pql(pql, trace=trace)

    def stop(self) -> None:
        if self.http is not None:
            self.http.stop()
        self.controller.stop()


def single_server_broker(
    table: str,
    segments,
    timeout_ms: float = 600_000.0,
    max_pending: int = 64,
    **server_kwargs,
):
    """One in-process server + broker over LocalTransport — the
    minimal serving topology every bench uses (bench.py,
    tools/config_bench.py).  The generous default timeout covers the
    first query's staging + compile on a tunneled chip.  Extra kwargs
    reach the ServerInstance (e.g. ``pipeline=False`` for the serial
    executor path); the instance is reachable as
    ``broker.local_servers[0]`` so benches can read lane/scheduler
    counters."""
    from pinot_tpu.broker.broker import BrokerRequestHandler
    from pinot_tpu.broker.routing import RoutingTableProvider

    server = ServerInstance("benchServer", max_pending=max_pending, **server_kwargs)
    for seg in segments:
        server.add_segment(table, seg)
    transport = LocalTransport()
    transport.register(("benchServer", 0), server.handle_request)
    routing = RoutingTableProvider()
    routing.update(table, {s.segment_name: {"benchServer": "ONLINE"} for s in segments})
    broker = BrokerRequestHandler(
        transport,
        {"benchServer": ("benchServer", 0)},
        routing=routing,
        timeout_ms=timeout_ms,
    )
    broker.local_servers = [server]
    return broker


# ---------------------------------------------------------------------------
# Self-stabilization chaos scenarios (shared by the CLI and the tier-1
# chaos tests): closed-loop load over an in-process cluster while a
# server is killed / drained / the whole fleet rolling-restarts, with
# the SelfStabilizer driven explicitly (run_once — deterministic, no
# background sleeps).
# ---------------------------------------------------------------------------


class ClosedLoopLoad:
    """N client threads issuing the same query back-to-back, classifying
    every response: ok (complete + correct), partial (transient
    ``partialResponse`` — allowed during healing), failed (wrong count
    or exceptions on a response claiming to be complete)."""

    def __init__(
        self, cluster: "InProcessCluster", pql: str, expected_docs: int,
        clients: int = 3,
    ) -> None:
        self.cluster = cluster
        self.pql = pql
        self.expected_docs = expected_docs
        self.clients = clients
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self.total = 0
        self.ok = 0
        self.partials = 0
        self.failed = 0
        self.failures: List[str] = []  # first few failure descriptions

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                resp = self.cluster.broker.handle_pql(self.pql)
            except Exception as e:  # a raised handler is always a failure
                with self._lock:
                    self.total += 1
                    self.failed += 1
                    if len(self.failures) < 8:
                        self.failures.append(f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                self.total += 1
                if resp.partial_response:
                    self.partials += 1
                elif resp.exceptions or resp.num_docs_scanned != self.expected_docs:
                    self.failed += 1
                    if len(self.failures) < 8:
                        self.failures.append(
                            f"docs={resp.num_docs_scanned}/{self.expected_docs} "
                            f"exceptions={[e.message for e in resp.exceptions][:2]}"
                        )
                else:
                    self.ok += 1

    def start(self) -> "ClosedLoopLoad":
        for i in range(self.clients):
            t = threading.Thread(target=self._loop, name=f"load-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        return {
            "queries": self.total,
            "okQueries": self.ok,
            "partialQueries": self.partials,
            "failedQueries": self.failed,
            "failures": list(self.failures),
        }


def _build_scenario_cluster(
    num_servers: int, replication: int, num_segments: int,
    data_dir: Optional[str] = None, seed: int = 5,
):
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.tools.datagen import make_test_schema, random_rows

    cluster = InProcessCluster(num_servers=num_servers, data_dir=data_dir)
    # scenarios drive rounds explicitly; act on death immediately
    cluster.controller.stabilizer.grace_s = 0.0
    schema = make_test_schema(with_mv=False)
    physical = cluster.add_offline_table(schema, replication=replication)
    rows = random_rows(schema, 260, seed=seed)
    total = 0
    for i in range(num_segments):
        # skewed sizes: the stabilizer's doc-weighted placement is what
        # keeps re-replication balanced under this skew
        n = 30 + 45 * (i % 5)
        cluster.upload(physical, build_segment(schema, rows[:n], physical, f"seg{i}"))
        total += n
    return cluster, physical, total


def _replication_state(cluster, physical: str, excluded=()) -> Dict[str, Any]:
    res = cluster.controller.resources
    ideal = res.get_ideal_state(physical)
    sizes = sorted({len(r) for r in ideal.values()}) if ideal else []
    return {
        "segments": len(ideal),
        "replicaSetSizes": sizes,
        "onExcluded": sum(
            1 for r in ideal.values() if any(s in r for s in excluded)
        ),
        "viewConverged": res.get_external_view(physical) == ideal,
    }


def run_kill_server_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, rounds: int = 2, victim: str = "server0",
    data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Kill one server under closed-loop load: zero failed queries
    (replica failover absorbs the loss), full replication restored by
    the stabilizer within ``rounds`` rounds, dead replicas dropped."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.15)  # warm: some queries complete pre-fault
        # kill: data plane goes dark, then the control plane declares the
        # death (the heartbeat-expiry path calls the same liveness flip)
        cluster.transport.set_down((victim, 0))
        cluster.controller.resources.set_instance_alive(victim, False)
        for _ in range(rounds):
            cluster.controller.stabilizer.run_once()
        time.sleep(0.15)  # healed steady state under load
        summary = load.stop()
        state = _replication_state(cluster, physical, excluded=[victim])
        final = cluster.query("SELECT count(*) FROM testTable")
        want = min(replication, num_servers - 1)
        return {
            "scenario": "kill-server",
            "victim": victim,
            "rounds": rounds,
            **summary,
            **state,
            "replicationRestored": state["replicaSetSizes"] == [want]
            and state["onExcluded"] == 0,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
            "stabilizer": cluster.controller.stabilizer.metrics.snapshot()["meters"],
        }
    finally:
        cluster.stop()


def _drain_one(cluster, name: str, max_rounds: int = 6) -> int:
    """Drain ``name`` and run stabilizer rounds until its replicas are
    fully migrated; returns rounds used."""
    cluster.controller.drain_instance(name)
    used = 0
    while used < max_rounds:
        if cluster.controller.drain_status(name)["drained"]:
            break
        cluster.controller.stabilizer.run_once()
        used += 1
    return used


def run_drain_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, victim: str = "server0", data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Drain one server under load: new routing stops covering it, the
    stabilizer migrates every replica off, the drain endpoint reports
    drained, and no query fails along the way."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.15)
        rounds = _drain_one(cluster, victim)
        status = cluster.controller.drain_status(victim)
        time.sleep(0.15)
        summary = load.stop()
        state = _replication_state(cluster, physical, excluded=[victim])
        final = cluster.query("SELECT count(*) FROM testTable")
        return {
            "scenario": "drain",
            "victim": victim,
            "roundsToDrain": rounds,
            "drainStatus": {k: status[k] for k in ("draining", "remainingSegments", "drained")},
            **summary,
            **state,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
            "finalComplete": not final.partial_response and not final.exceptions,
        }
    finally:
        cluster.stop()


def run_rolling_restart_scenario(
    num_servers: int = 3, replication: int = 2, num_segments: int = 6,
    clients: int = 3, data_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Rolling restart of EVERY server under load, one at a time:
    drain -> (replicas migrate) -> restart (down+dead, then back) ->
    undrain -> next.  Zero failed queries, zero permanent segment loss."""
    cluster, physical, total = _build_scenario_cluster(
        num_servers, replication, num_segments, data_dir
    )
    res = cluster.controller.resources
    try:
        load = ClosedLoopLoad(
            cluster, "SELECT count(*) FROM testTable", total, clients
        ).start()
        time.sleep(0.1)
        rounds_per_server: Dict[str, int] = {}
        for server in [s.name for s in cluster.servers]:
            rounds_per_server[server] = _drain_one(cluster, server)
            assert cluster.controller.drain_status(server)["drained"], server
            # "restart": the process goes away (data plane down, death
            # declared) and comes back — it holds nothing, so this is
            # invisible to queries
            cluster.transport.set_down((server, 0))
            res.set_instance_alive(server, False)
            cluster.transport.set_down((server, 0), False)
            res.set_instance_alive(server, True)
            cluster.controller.undrain_instance(server)
            cluster.controller.stabilizer.run_once()
        time.sleep(0.1)
        summary = load.stop()
        state = _replication_state(cluster, physical)
        final = cluster.query("SELECT count(*) FROM testTable")
        return {
            "scenario": "rolling-restart",
            "roundsPerServer": rounds_per_server,
            **summary,
            **state,
            "noSegmentLoss": state["replicaSetSizes"] == [replication]
            and final.num_docs_scanned == total
            and not final.partial_response,
            "finalDocs": final.num_docs_scanned,
            "expectedDocs": total,
        }
    finally:
        cluster.stop()


SCENARIOS = {
    "kill-server": run_kill_server_scenario,
    "drain": run_drain_scenario,
    "rolling-restart": run_rolling_restart_scenario,
}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", choices=sorted(SCENARIOS), required=True)
    p.add_argument("--servers", type=int, default=3)
    p.add_argument("--replication", type=int, default=2)
    p.add_argument("--segments", type=int, default=6)
    p.add_argument("--clients", type=int, default=3)
    args = p.parse_args(argv)
    out = SCENARIOS[args.scenario](
        num_servers=args.servers,
        replication=args.replication,
        num_segments=args.segments,
        clients=args.clients,
    )
    print(json.dumps(out, indent=2))
    return 0 if out["failedQueries"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
