"""Quickstarts: offline (baseballStats), realtime (meetupRsvp-shaped
stream), hybrid — the ``Quickstart.java:33`` / ``RealtimeQuickStart.java``
/ ``HybridQuickstart.java`` analogs: stand up an in-process cluster,
load data, run sample queries, optionally keep an HTTP broker running.
"""
from __future__ import annotations

import json
import time
from typing import List, Optional

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.realtime.stream import MemoryStreamProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.startree.builder import StarTreeBuilderConfig
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import baseball_rows, baseball_schema

OFFLINE_SAMPLE_QUERIES = [
    "SELECT count(*) FROM baseballStats",
    "SELECT sum(runs) FROM baseballStats GROUP BY playerName TOP 5",
    "SELECT sum(hits), sum(homeRuns) FROM baseballStats WHERE teamID = 'BOS'",
    "SELECT avg(runs) FROM baseballStats GROUP BY league",
    "SELECT playerName, runs FROM baseballStats ORDER BY runs DESC LIMIT 5",
]


def run_offline_quickstart(
    num_rows: int = 10_000,
    num_segments: int = 4,
    startree: bool = False,
    http: bool = False,
    verbose: bool = True,
) -> InProcessCluster:
    """baseballStats offline quickstart: CSV-shaped data -> segments ->
    cluster -> PQL over HTTP (the minimum end-to-end slice, SURVEY §7)."""
    schema = baseball_schema()
    rows = baseball_rows(num_rows)
    cluster = InProcessCluster(num_servers=2, http=http)
    physical = cluster.add_offline_table(schema)

    chunk = max(1, len(rows) // num_segments)
    cfg = StarTreeBuilderConfig(max_leaf_records=100) if startree else None
    for i in range(num_segments):
        part = rows[i * chunk : (i + 1) * chunk if i < num_segments - 1 else len(rows)]
        seg = build_segment(
            schema, part, physical, f"baseballStats_{i}", startree_config=cfg
        )
        cluster.upload(physical, seg)

    if verbose:
        for pql in OFFLINE_SAMPLE_QUERIES:
            resp = cluster.query(pql)
            print(f"\n>>> {pql}")
            print(json.dumps(resp.to_json(), indent=2)[:1200])
        if http:
            print(f"\nbroker listening on http://127.0.0.1:{cluster.http.port}/query")
    return cluster


def meetup_schema() -> Schema:
    return Schema(
        "meetupRsvp",
        dimensions=[
            FieldSpec("venue_name", DataType.STRING),
            FieldSpec("event_name", DataType.STRING),
            FieldSpec("group_city", DataType.STRING),
        ],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )


def run_realtime_quickstart(
    num_events: int = 2000, http: bool = False, verbose: bool = True
) -> InProcessCluster:
    """meetupRsvp realtime quickstart: stream -> consuming segment ->
    live windowed count queries (RealtimeQuickStart.java analog)."""
    import random

    rng = random.Random(1)
    schema = meetup_schema()
    cluster = InProcessCluster(num_servers=1, http=http)
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=500)

    cities = ["sf", "nyc", "seattle", "austin", "chicago"]
    now = int(time.time() * 1000)
    for i in range(num_events):
        stream.produce(
            {
                "venue_name": f"venue{rng.randrange(20)}",
                "event_name": f"event{rng.randrange(8)}",
                "group_city": rng.choice(cities),
                "rsvp_count": rng.randint(1, 5),
                "mtime": now + i,
            }
        )

    # drive consumption + commits (a background loop in a deployment)
    from pinot_tpu.realtime.llc import make_segment_name

    seq = 0
    while True:
        seg = make_segment_name(physical, 0, seq)
        dms = cluster.controller.realtime_manager.consumers_of(seg)
        if not dms:
            break
        dm = dms[0]
        consumed = dm.consume_step(max_rows=10_000)
        if dm.threshold_reached:
            dm.try_commit()
            seq += 1
        elif consumed == 0:
            break

    if verbose:
        for pql in [
            "SELECT count(*) FROM meetupRsvp",
            "SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY group_city",
            "SELECT count(*) FROM meetupRsvp GROUP BY event_name TOP 3",
        ]:
            resp = cluster.query(pql)
            print(f"\n>>> {pql}")
            print(json.dumps(resp.to_json(), indent=2)[:900])
    return cluster
