"""Quickstarts: offline (baseballStats), realtime (meetupRsvp-shaped
stream), hybrid — the ``Quickstart.java:33`` / ``RealtimeQuickStart.java``
/ ``HybridQuickstart.java`` analogs: stand up an in-process cluster,
load data, run sample queries, optionally keep an HTTP broker running.
"""
from __future__ import annotations

import json
import time
from typing import List, Optional

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec
from pinot_tpu.realtime.stream import MemoryStreamProvider
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.startree.builder import StarTreeBuilderConfig
from pinot_tpu.tools.cluster_harness import InProcessCluster
from pinot_tpu.tools.datagen import baseball_rows, baseball_schema

# demo clusters serve interactively after the samples print, so the
# timeout only caps the worst case; it must cover a cold-chip compile
_COLD_TIMEOUT_MS = 300_000.0


def drain_stream(cluster: InProcessCluster, physical: str, max_rows: int = 10_000) -> int:
    """Consume/seal/roll partition 0 until the stream is dry (the
    background consume loop a deployment runs); returns sealed count."""
    from pinot_tpu.realtime.llc import make_segment_name

    seq = 0
    while True:
        seg = make_segment_name(physical, 0, seq)
        dms = cluster.controller.realtime_manager.consumers_of(seg)
        if not dms:
            break
        dm = dms[0]
        consumed = dm.consume_step(max_rows=max_rows)
        if dm.threshold_reached:
            dm.try_commit()
            seq += 1
        elif consumed == 0:
            break
    return seq

OFFLINE_SAMPLE_QUERIES = [
    "SELECT count(*) FROM baseballStats",
    "SELECT sum(runs) FROM baseballStats GROUP BY playerName TOP 5",
    "SELECT sum(hits), sum(homeRuns) FROM baseballStats WHERE teamID = 'BOS'",
    "SELECT avg(runs) FROM baseballStats GROUP BY league",
    "SELECT playerName, runs FROM baseballStats ORDER BY runs DESC LIMIT 5",
]


def run_offline_quickstart(
    num_rows: int = 10_000,
    num_segments: int = 4,
    startree: bool = False,
    http: bool = False,
    verbose: bool = True,
) -> InProcessCluster:
    """baseballStats offline quickstart: CSV-shaped data -> segments ->
    cluster -> PQL over HTTP (the minimum end-to-end slice, SURVEY §7)."""
    schema = baseball_schema()
    rows = baseball_rows(num_rows)
    # each demo query is a fresh plan shape: on a cold accelerator the
    # first compile takes 20-40s, so the serving default (15s) would
    # time out every sample query (the bench path does the same)
    cluster = InProcessCluster(num_servers=2, http=http, timeout_ms=_COLD_TIMEOUT_MS)
    physical = cluster.add_offline_table(schema)

    chunk = max(1, len(rows) // num_segments)
    cfg = StarTreeBuilderConfig(max_leaf_records=100) if startree else None
    for i in range(num_segments):
        part = rows[i * chunk : (i + 1) * chunk if i < num_segments - 1 else len(rows)]
        seg = build_segment(
            schema, part, physical, f"baseballStats_{i}", startree_config=cfg
        )
        cluster.upload(physical, seg)

    if verbose:
        for pql in OFFLINE_SAMPLE_QUERIES:
            resp = cluster.query(pql)
            print(f"\n>>> {pql}")
            print(json.dumps(resp.to_json(), indent=2)[:1200])
        if http:
            print(f"\nbroker listening on http://127.0.0.1:{cluster.http.port}/query")
    return cluster


def meetup_schema() -> Schema:
    return Schema(
        "meetupRsvp",
        dimensions=[
            FieldSpec("venue_name", DataType.STRING),
            FieldSpec("event_name", DataType.STRING),
            FieldSpec("group_city", DataType.STRING),
        ],
        metrics=[FieldSpec("rsvp_count", DataType.INT, FieldType.METRIC)],
        time_field=TimeFieldSpec("mtime", DataType.LONG, time_unit="MILLISECONDS"),
    )


def run_realtime_quickstart(
    num_events: int = 2000, http: bool = False, verbose: bool = True
) -> InProcessCluster:
    """meetupRsvp realtime quickstart: stream -> consuming segment ->
    live windowed count queries (RealtimeQuickStart.java analog)."""
    import random

    rng = random.Random(1)
    schema = meetup_schema()
    cluster = InProcessCluster(num_servers=1, http=http, timeout_ms=_COLD_TIMEOUT_MS)
    stream = MemoryStreamProvider(num_partitions=1)
    physical = cluster.add_realtime_table(schema, stream, rows_per_segment=500)

    cities = ["sf", "nyc", "seattle", "austin", "chicago"]
    now = int(time.time() * 1000)
    for i in range(num_events):
        stream.produce(
            {
                "venue_name": f"venue{rng.randrange(20)}",
                "event_name": f"event{rng.randrange(8)}",
                "group_city": rng.choice(cities),
                "rsvp_count": rng.randint(1, 5),
                "mtime": now + i,
            }
        )

    drain_stream(cluster, physical)

    if verbose:
        for pql in [
            "SELECT count(*) FROM meetupRsvp",
            "SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY group_city",
            "SELECT count(*) FROM meetupRsvp GROUP BY event_name TOP 3",
        ]:
            resp = cluster.query(pql)
            print(f"\n>>> {pql}")
            print(json.dumps(resp.to_json(), indent=2)[:900])
    return cluster


def run_hybrid_quickstart(
    num_offline: int = 1500, num_realtime: int = 800, http: bool = False, verbose: bool = True
) -> InProcessCluster:
    """Hybrid quickstart (``HybridQuickstart.java`` analog): the SAME
    logical table served by an OFFLINE side (historical segments) and a
    REALTIME side (live stream), federated at query time by the offline
    max-time boundary — offline answers <= boundary, realtime answers
    the fresh tail, each row counted exactly once."""
    import random

    rng = random.Random(3)
    schema = meetup_schema()
    cluster = InProcessCluster(num_servers=2, http=http, timeout_ms=_COLD_TIMEOUT_MS)
    cities = ["sf", "nyc", "seattle", "austin", "chicago"]
    base = int(time.time() * 1000) - 86_400_000  # yesterday

    def event(i: int) -> dict:
        return {
            "venue_name": f"venue{rng.randrange(20)}",
            "event_name": f"event{rng.randrange(8)}",
            "group_city": rng.choice(cities),
            "rsvp_count": rng.randint(1, 5),
            "mtime": base + i * 1000,
        }

    # offline side: two historical segments
    offline = cluster.add_offline_table(schema, table_name="meetupRsvp")
    rows = [event(i) for i in range(num_offline)]
    half = num_offline // 2
    for name, part in (("hist0", rows[:half]), ("hist1", rows[half:])):
        cluster.upload(offline, build_segment(schema, part, offline, name))

    # realtime side: the live tail STARTS BEFORE the boundary to prove
    # overlap dedup, then extends past it
    stream = MemoryStreamProvider(num_partitions=1)
    rt_physical = cluster.add_realtime_table(schema, stream, rows_per_segment=10_000)
    for i in range(num_offline - 100, num_offline + num_realtime):
        stream.produce(event(i))
    # consume/seal/roll until dry, so row counts past one segment's
    # budget still land
    drain_stream(cluster, rt_physical, max_rows=1_000_000)

    if verbose:
        for pql in [
            "SELECT count(*) FROM meetupRsvp",
            "SELECT max(mtime) FROM meetupRsvp",
            "SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY group_city TOP 5",
        ]:
            resp = cluster.query(pql)
            print(f"\n>>> {pql}")
            print(json.dumps(resp.to_json(), indent=2)[:900])
        if http:
            print(f"\nbroker listening on http://127.0.0.1:{cluster.http.port}/query")
    return cluster


def run_network_realtime_quickstart(
    num_events: int = 2000,
    verbose: bool = True,
    data_dir: Optional[str] = None,
    consumer_type: str = "lowlevel",
    stream_protocol: str = "native",
):
    """Networked realtime quickstart: a real TCP stream-broker process
    boundary (realtime/netstream.py), a controller + server + broker as
    separate OS processes, REALTIME table created over REST, rows
    produced over TCP, counts queried through the broker HTTP port —
    the full reference deployment shape with the stream broker playing
    Kafka's role.

    ``stream_protocol="kafka"`` fronts the stream broker with the Kafka
    v0 wire-protocol shim (realtime/kafka.py) and creates the table
    with ``stream_type="kafka"``: the server processes then consume
    through the Kafka binary protocol (Metadata/ListOffsets/Fetch),
    exactly as they would against a real Kafka 0.8+ deployment
    (``SimpleConsumerWrapper.java`` parity)."""
    import random
    import subprocess
    import sys
    import tempfile
    import urllib.request

    from pinot_tpu.common.tableconfig import StreamConfig, TableConfig
    from pinot_tpu.realtime.netstream import NetworkStreamProvider, StreamBrokerServer

    root = data_dir or tempfile.mkdtemp(prefix="pinot_tpu_netrt_")
    stream_broker = StreamBrokerServer(log_dir=f"{root}/streamlog")
    stream_broker.start()
    host, port = stream_broker.address
    producer = NetworkStreamProvider(host, port, "meetupRsvp")
    producer.create_topic(1 if consumer_type == "lowlevel" else 2)
    kafka_shim = None
    if stream_protocol == "kafka":
        from pinot_tpu.realtime.kafka import KafkaProtocolShim

        kafka_shim = KafkaProtocolShim(stream_broker).start()

    def spawn(args, prefix="READY"):
        import os as _os
        import select

        env = dict(_os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "pinot_tpu.tools.admin", *args],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
        )
        deadline = time.time() + 90
        while time.time() < deadline:
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if ready:
                line = proc.stdout.readline()
                if line.startswith(prefix):
                    return proc, line.split()[-1]
            if proc.poll() is not None:
                raise RuntimeError(f"process exited early: {args}")
        proc.kill()
        raise RuntimeError(f"no READY from {args}")

    procs = []
    try:
        ctrl, ctrl_url = spawn(["StartController", "-port", "0", "-data-dir", f"{root}/store"])
        procs.append(ctrl)
        srv, _ = spawn(["StartServer", "-controller", ctrl_url, "-name", "qs0",
                        "-data-dir", f"{root}/cache"])
        procs.append(srv)
        brk, broker_url = spawn(["StartBroker", "-controller", ctrl_url, "-port", "0"])
        procs.append(brk)

        def post(url, payload):
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        schema = meetup_schema()
        post(ctrl_url + "/schemas", schema.to_json())
        if kafka_shim is not None:
            k_host, k_port = kafka_shim.address
            stream_cfg = StreamConfig(
                stream_type="kafka",
                topic="meetupRsvp",
                rows_per_segment=500,
                consumer_type=consumer_type,
                properties={"host": k_host, "port": k_port},
            )
        else:
            stream_cfg = StreamConfig(
                stream_type="network",
                topic="meetupRsvp",
                rows_per_segment=500,
                consumer_type=consumer_type,
                properties={"host": host, "port": port},
            )
        config = TableConfig(
            table_name="meetupRsvp",
            table_type="REALTIME",
            stream=stream_cfg,
        )
        post(ctrl_url + "/tables", config.to_json())

        rng = random.Random(1)
        now = int(time.time() * 1000)
        producer.produce_batch(
            [
                {
                    "venue_name": f"venue{rng.randrange(20)}",
                    "event_name": f"event{rng.randrange(8)}",
                    "group_city": rng.choice(["sf", "nyc", "seattle", "austin"]),
                    "rsvp_count": rng.randint(1, 5),
                    "mtime": now + i,
                }
                for i in range(num_events)
            ]
        )

        deadline = time.time() + 120
        count = 0
        while time.time() < deadline:
            resp = post(broker_url + "/query", {"pql": "SELECT count(*) FROM meetupRsvp"})
            count = resp.get("numDocsScanned", 0)
            if count >= num_events and not resp.get("exceptions"):
                break
            time.sleep(0.5)
        if verbose:
            for pql in [
                "SELECT count(*) FROM meetupRsvp",
                "SELECT sum(rsvp_count) FROM meetupRsvp GROUP BY group_city",
            ]:
                resp = post(broker_url + "/query", {"pql": pql})
                print(f"\n>>> {pql}")
                print(json.dumps(resp, indent=2)[:900])
        return count
    finally:
        if kafka_shim is not None:
            kafka_shim.stop()
        stream_broker.stop()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
