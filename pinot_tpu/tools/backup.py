"""Cluster backup & disaster restore (``python -m pinot_tpu.tools.backup``).

The reference survives a controller-host loss because the durable
state lives elsewhere: cluster metadata in the ZooKeeper ensemble,
segment bytes in the deep store (NFS/HDFS).  Our single-node analog
keeps both under the controller's data dir, so this tool provides the
missing leg: a **consistent online backup** of the metadata plane
(property-store record mirror + op journal + snapshot) plus a segment
manifest with byte-level CRCs, and a **restore** path that rebuilds a
brand-new controller from archive + deep store alone.

Consistency while the cluster serves: every property-store mutation
runs under the store's cross-process fence flock (``.fence.lock``), so
holding that same flock for the duration of the metadata copy yields a
point-in-time image — no torn record, no journal/mirror skew.  Segment
files are immutable once written (tmp+rename installs), so the
manifest pass needs no lock.

Restore boots the archive's metadata into a fresh data dir and
verifies the deep store against the manifest; anything missing or
rotted is reported (and healed later by the ``DeepStoreScrubber``
via reverse replication from live servers).  A new ``Controller`` over
the restored dir then claims the NEXT epoch past the journaled one —
so the PR 9 fencing invariant survives the disaster: a zombie
pre-disaster controller's writes are still rejected.
"""
from __future__ import annotations

import argparse
import fcntl
import json
import os
import shutil
import sys
import tarfile
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "MANIFEST.json"
METADATA_PREFIX = "metadata"
_FENCE_LOCK_FILE = ".fence.lock"


def _copy_metadata_consistent(ps_dir: str, staging: str) -> None:
    """Copy the property-store tree under its own fence flock: writers
    take the same lock per mutation, so the image is point-in-time."""
    lock_path = os.path.join(ps_dir, _FENCE_LOCK_FILE)
    with open(lock_path, "a+b") as lock_fd:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        try:
            shutil.copytree(
                ps_dir,
                staging,
                ignore=shutil.ignore_patterns(_FENCE_LOCK_FILE, "*.tmp"),
            )
        finally:
            fcntl.flock(lock_fd, fcntl.LOCK_UN)


def _staged_journal_info(staging: str) -> Dict[str, Any]:
    from pinot_tpu.controller.journal import JOURNAL_DIR_NAME, LOG_NAME, SNAPSHOT_NAME

    jdir = os.path.join(staging, JOURNAL_DIR_NAME)
    log = os.path.join(jdir, LOG_NAME)
    snap = os.path.join(jdir, SNAPSHOT_NAME)
    info: Dict[str, Any] = {"journalBytes": 0, "snapshotSeq": 0}
    if os.path.exists(log):
        info["journalBytes"] = os.path.getsize(log)
    if os.path.exists(snap):
        try:
            with open(snap) as f:
                info["snapshotSeq"] = int(json.load(f).get("seq", 0))
        except (ValueError, OSError):
            pass
    return info


def _staged_epoch(staging: str) -> int:
    path = os.path.join(staging, "cluster", "epoch.json")
    try:
        with open(path) as f:
            return int(json.load(f).get("epoch", 0))
    except (ValueError, OSError):
        return 0


def create_backup(data_dir: str, out_path: str) -> Dict[str, Any]:
    """Write a consistent ``.tar.gz`` archive of the metadata plane +
    a CRC'd manifest of the deep store, while the cluster serves."""
    from pinot_tpu.controller.store import SegmentStore

    t0 = time.monotonic()
    ps_dir = os.path.join(data_dir, "property_store")
    if not os.path.isdir(ps_dir):
        raise FileNotFoundError(f"no property store at {ps_dir}")
    staging = tempfile.mkdtemp(prefix="pinot_backup_")
    staged_meta = os.path.join(staging, METADATA_PREFIX)
    try:
        _copy_metadata_consistent(ps_dir, staged_meta)
        seg_manifest = SegmentStore(os.path.join(data_dir, "segments")).manifest()
        manifest: Dict[str, Any] = {
            "version": 1,
            "createdAtMs": int(time.time() * 1000),
            "epoch": _staged_epoch(staged_meta),
            "segments": seg_manifest,
        }
        manifest.update(_staged_journal_info(staged_meta))
        manifest_path = os.path.join(staging, MANIFEST_NAME)
        with open(manifest_path, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
        tmp_out = out_path + ".tmp"
        with tarfile.open(tmp_out, "w:gz") as tar:
            tar.add(manifest_path, arcname=MANIFEST_NAME)
            tar.add(staged_meta, arcname=METADATA_PREFIX)
        os.replace(tmp_out, out_path)
    finally:
        shutil.rmtree(staging, ignore_errors=True)
    n_segments = sum(len(v) for v in manifest["segments"].values())
    return {
        "archive": out_path,
        "archiveBytes": os.path.getsize(out_path),
        "journalBytes": manifest["journalBytes"],
        "snapshotSeq": manifest["snapshotSeq"],
        "epoch": manifest["epoch"],
        "segments": n_segments,
        "backupSeconds": time.monotonic() - t0,
    }


def _safe_members(tar: tarfile.TarFile) -> List[tarfile.TarInfo]:
    """Reject path-traversal members (absolute paths, '..' components,
    links) before extraction."""
    out = []
    for m in tar.getmembers():
        name = m.name
        if name.startswith("/") or os.path.isabs(name):
            raise ValueError(f"unsafe archive member (absolute): {name}")
        if any(part == ".." for part in name.split("/")):
            raise ValueError(f"unsafe archive member (traversal): {name}")
        if m.issym() or m.islnk():
            raise ValueError(f"unsafe archive member (link): {name}")
        out.append(m)
    return out


def restore_backup(
    archive_path: str, data_dir: str, overwrite: bool = False
) -> Dict[str, Any]:
    """Rebuild the metadata plane from an archive and verify the deep
    store against the manifest.

    Does NOT construct the controller: the caller boots a fresh
    ``Controller(data_dir)`` afterwards, which replays the restored
    snapshot+journal, claims the next epoch (fencing preserved), and
    recovers tables/ideal states/drain flags/realtime offsets."""
    t0 = time.monotonic()
    ps_dir = os.path.join(data_dir, "property_store")
    if os.path.isdir(ps_dir) and os.listdir(ps_dir) and not overwrite:
        raise FileExistsError(
            f"refusing to restore over non-empty {ps_dir} (pass overwrite)"
        )
    with tarfile.open(archive_path, "r:gz") as tar:
        members = _safe_members(tar)
        with tempfile.TemporaryDirectory(prefix="pinot_restore_") as td:
            tar.extractall(td, members=members)
            with open(os.path.join(td, MANIFEST_NAME)) as f:
                manifest = json.load(f)
            extracted_meta = os.path.join(td, METADATA_PREFIX)
            if not os.path.isdir(extracted_meta):
                raise ValueError(f"archive {archive_path} has no metadata tree")
            if os.path.isdir(ps_dir):
                shutil.rmtree(ps_dir)
            os.makedirs(os.path.dirname(os.path.abspath(ps_dir)), exist_ok=True)
            shutil.copytree(extracted_meta, ps_dir)

    # verify the deep store against the manifest's byte-level CRCs;
    # damage is reported (and later healed by the scrubber), not fatal
    from pinot_tpu.controller.store import SegmentStore

    store = SegmentStore(os.path.join(data_dir, "segments"))
    verified = 0
    missing: List[str] = []
    corrupt: List[str] = []
    for table, segs in (manifest.get("segments") or {}).items():
        for seg, entry in segs.items():
            path = store.segment_file_path(table, seg)
            if not os.path.exists(path):
                missing.append(f"{table}/{seg}")
                continue
            with open(path, "rb") as f:
                crc = zlib.crc32(f.read()) & 0xFFFFFFFF
            if int(entry.get("crc32", 0)) not in (0, crc):
                corrupt.append(f"{table}/{seg}")
                continue
            verified += 1
    return {
        "restored": True,
        "archive": archive_path,
        "epoch": manifest.get("epoch", 0),
        "snapshotSeq": manifest.get("snapshotSeq", 0),
        "journalBytes": manifest.get("journalBytes", 0),
        "segmentsVerified": verified,
        "segmentsMissing": missing,
        "segmentsCorrupt": corrupt,
        "restoreSeconds": time.monotonic() - t0,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("backup", help="write a consistent archive of a live cluster")
    b.add_argument("--data-dir", required=True)
    b.add_argument("--out", required=True, help="archive path (.tar.gz)")
    r = sub.add_parser("restore", help="rebuild a data dir's metadata from an archive")
    r.add_argument("--archive", required=True)
    r.add_argument("--data-dir", required=True)
    r.add_argument("--overwrite", action="store_true")
    args = ap.parse_args(argv)
    if args.cmd == "backup":
        out = create_backup(args.data_dir, args.out)
    else:
        out = restore_backup(args.archive, args.data_dir, overwrite=args.overwrite)
    json.dump(out, sys.stdout, indent=2, sort_keys=True)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
