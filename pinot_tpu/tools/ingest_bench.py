"""Parallel realtime ingest benchmark: N partition consumers against
the stream broker, plus query latency DURING sustained ingest.

The reference measures realtime consumption as rows/s through one
segment's ``index()`` loop (``BenchmarkRealtimeConsumptionSpeed.java:38``).
Production ingest is N partition consumers spread across server
processes, each pulling batches from the stream broker by offset and
indexing into its partition's mutable segment — so this bench runs the
REAL consumer path (TCP fetch -> JSON decode -> encode -> commit) with
one OS process per partition:

  1. a ``StreamBrokerServer`` (realtime/netstream.py) holds an
     N-partition numeric-heavy topic, pre-produced;
  2. N-1 consumer subprocesses each drain one partition into a
     ``MutableSegment`` and report their own rows/s;
  3. partition 0 is consumed IN-PROCESS on a thread while a broker
     serves its live mutable segment — query p50/p99 is measured
     against it during the sustained ingest window.

Aggregate rows/s = total rows / slowest consumer's drain time (the
honest cluster-level number: ingestion finishes when the last
partition catches up).

Usage:
  python -m pinot_tpu.tools.ingest_bench -partitions 4 -rows 1000000

``--ladder`` (r15) runs the partition-parallel consumer ladder instead:
1/2/4 consumers — each a REAL ``RealtimeSegmentDataManager`` driven by
an ``IngestConsumerPool`` (realtime/pool.py) in its own OS process,
the production shape of consumers spread across server processes —
draining pre-produced partitions, reporting per-rung aggregate rows/s
and lag drain.  The 1-consumer baseline pins broker AND consumer to a
single core: that is the single-consumer LLC ceiling as INGEST_r5
committed it (``cpu_cores: 1``), and the number partition-parallel
aggregate ingest must beat.  Emits a perf-gateable document
(``metric: ingest_parallel_rows_per_sec``; see
``tools/perf_gate.py INGEST_METRIC_SPECS`` / ``INGEST_r15.json``).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import List

import numpy as np

from pinot_tpu.common.schema import (
    DataType,
    FieldSpec,
    FieldType,
    Schema,
    TimeFieldSpec,
)

TOPIC = "adclicks"
FETCH_ROWS = 4096
BLOCK_ROWS = 65536  # columnar block size: amortizes RTT, keeps encode batches fat

# the committed single-consumer LLC ceiling this arc set out to beat:
# INGEST_r5.json llc_consumer_columnar_rows_per_sec (the production
# RealtimeSegmentDataManager measured through its own consume_step
# loop, cpu_cores=1).  The ladder reports its aggregate against this
# reference alongside the same-host parallel_vs_single ratio.
R5_SINGLE_CONSUMER_CEILING = 1_288_021.0


def adclick_schema() -> Schema:
    """Numeric-heavy schema (the reference's consumption benchmark uses
    a numeric-dominated row too)."""
    return Schema(
        "adclicks",
        dimensions=[
            FieldSpec("campaign_id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("site_id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("user_id", DataType.LONG, FieldType.DIMENSION),
        ],
        metrics=[
            FieldSpec("clicks", DataType.INT, FieldType.METRIC),
            FieldSpec("cost", DataType.FLOAT, FieldType.METRIC),
        ],
        time_field=TimeFieldSpec("ts", DataType.LONG, time_unit="MILLISECONDS"),
    )


def gen_columns(n: int, seed: int) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "campaign_id": rng.integers(0, 1024, n, dtype=np.int64),
        "site_id": rng.integers(0, 128, n, dtype=np.int64),
        "user_id": rng.integers(0, 1 << 22, n, dtype=np.int64),
        "clicks": rng.integers(0, 16, n, dtype=np.int64),
        "cost": np.round(rng.random(n) * 10, 3),
        "ts": 1_700_000_000_000 + np.arange(n, dtype=np.int64),
    }


def drain_partition(host: str, port: int, partition: int, expect_rows: int, seg=None):
    """The real consumer loop: offset-addressed columnar TCP fetch +
    vectorized dictionary encode.  Returns (rows, seconds, segment)."""
    from pinot_tpu.realtime.mutable import MutableSegment
    from pinot_tpu.realtime.netstream import NetworkStreamProvider

    provider = NetworkStreamProvider(host, port, TOPIC)
    if seg is None:
        seg = MutableSegment(adclick_schema(), f"rt{partition}", "adclicks")
    offset = 0
    total = 0
    t0 = time.perf_counter()
    while total < expect_rows:
        cols, n, offset = provider.fetch_columns(partition, offset)
        if n == 0:
            time.sleep(0.001)
            continue
        seg.index_columns(cols)
        total += n
    return total, time.perf_counter() - t0, seg


def worker_main() -> None:
    host, port, partition, expect = (
        sys.argv[2],
        int(sys.argv[3]),
        int(sys.argv[4]),
        int(sys.argv[5]),
    )
    total, secs, _seg = drain_partition(host, port, partition, expect)
    print(json.dumps({"partition": partition, "rows": total, "seconds": round(secs, 3)}), flush=True)


def broker_main() -> None:
    """The stream broker as its OWN process: serving byte-splice fetches
    must not share a GIL with the query engine or a consumer.
    ``PINOT_TPU_LADDER_BROKER_CORE`` pins the WHOLE process (set before
    any serving thread spawns, so every thread inherits it) — the
    ladder's single-core baseline rung uses this."""
    from pinot_tpu.realtime.netstream import StreamBrokerServer

    core = os.environ.get("PINOT_TPU_LADDER_BROKER_CORE")
    if core:
        os.sched_setaffinity(0, {int(core)})
    partitions = int(sys.argv[2])
    srv = StreamBrokerServer()
    srv.start()
    srv.create_topic(TOPIC, partitions)
    print(json.dumps({"port": srv.address[1]}), flush=True)
    try:
        time.sleep(3600)
    except KeyboardInterrupt:
        pass


def ladder_worker_main() -> None:
    """One ladder consumer process: the real r15 consumer machinery —
    ``RealtimeSegmentDataManager`` (columnar fetch path) registered
    with an ``IngestConsumerPool`` — draining one partition.  argv:
    --ladder-worker host port partition rows core(-1=unpinned)."""
    host, port, partition, rows, core = (
        sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        int(sys.argv[5]), int(sys.argv[6]),
    )
    if core >= 0:
        os.sched_setaffinity(0, {core})
    from pinot_tpu.realtime.llc import RealtimeSegmentDataManager
    from pinot_tpu.realtime.netstream import NetworkStreamProvider
    from pinot_tpu.realtime.pool import IngestConsumerPool

    class _BenchServer:  # the attrs the DM reads; no metrics/governor
        name = f"ladder{partition}"
        metrics = None
        ingest_backpressure = None
        result_cache = None

    stream = NetworkStreamProvider(host, port, TOPIC)
    dm = RealtimeSegmentDataManager(
        server=_BenchServer(),
        manager=None,  # no commits: rows_per_segment is never reached
        table="adclicks",
        segment_name=f"adclicks__{partition}__0",
        schema=adclick_schema(),
        stream=stream,
        partition=partition,
        start_offset=0,
        rows_per_segment=rows + 1,
    )
    dm.step_rows = BLOCK_ROWS  # consume whole columnar blocks per step
    pool = IngestConsumerPool(workers=1, name=f"ladder{partition}")
    # start barrier: every rung sibling finishes its (CPU-heavy)
    # interpreter startup BEFORE any of them drains, or the measured
    # window of one consumer overlaps another's imports
    print("READY", flush=True)
    sys.stdin.readline()
    t0 = time.perf_counter()
    pool.add(dm, key=partition)
    while dm.offset < rows:
        time.sleep(0.002)
    secs = time.perf_counter() - t0
    lag = dm.lag()
    pool.stop()
    print(
        json.dumps(
            {
                "partition": partition,
                "rows": dm.mutable.num_docs,
                "seconds": round(secs, 3),
                "lagFinal": lag,
            }
        ),
        flush=True,
    )


def ladder_main(args) -> None:
    """The 1/2/4-consumer partition-parallel ladder (r15)."""
    from pinot_tpu.realtime.netstream import NetworkStreamProvider

    env = dict(os.environ)
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    cores = sorted(os.sched_getaffinity(0))
    partitions = max(args.partitions, max(args.ladder_rungs))
    host = "127.0.0.1"

    def start_broker(n_partitions: int, pin_core=None):
        broker_env = dict(env)
        if pin_core is not None:
            broker_env["PINOT_TPU_LADDER_BROKER_CORE"] = str(pin_core)
        proc = subprocess.Popen(
            [sys.executable, "-m", "pinot_tpu.tools.ingest_bench",
             "--broker", str(n_partitions)],
            stdout=subprocess.PIPE, text=True, env=broker_env,
        )
        return proc, int(json.loads(proc.stdout.readline())["port"])

    def produce_all(port: int, n_partitions: int) -> None:
        def produce(p: int) -> None:
            provider = NetworkStreamProvider(host, port, TOPIC)
            cols = gen_columns(args.rows, seed=17 + p)
            for i in range(0, args.rows, BLOCK_ROWS):
                provider.produce_columns(
                    {c: a[i : i + BLOCK_ROWS] for c, a in cols.items()},
                    partition=p,
                )

        producers = [
            threading.Thread(target=produce, args=(p,))
            for p in range(n_partitions)
        ]
        for t in producers:
            t.start()
        for t in producers:
            t.join()

    def rung(port: int, consumers: int, pin_core=None):
        """Drain ``consumers`` partitions concurrently, one consumer
        process per partition (fetches are non-destructive, so rungs
        against the shared broker re-drain from offset 0)."""
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "pinot_tpu.tools.ingest_bench",
                 "--ladder-worker", host, str(port), str(p), str(args.rows),
                 str(pin_core if pin_core is not None else -1)],
                stdout=subprocess.PIPE, stdin=subprocess.PIPE,
                text=True, env=env,
            )
            for p in range(consumers)
        ]
        for pr in procs:  # wait for every sibling's READY, then GO
            assert pr.stdout.readline().strip() == "READY"
        for pr in procs:
            pr.stdin.write("GO\n")
            pr.stdin.flush()
        outs = [
            json.loads(pr.communicate(timeout=900)[0].splitlines()[-1])
            for pr in procs
        ]
        wall = max(o["seconds"] for o in outs)
        total = sum(o["rows"] for o in outs)
        return {
            "consumers": consumers,
            "rows": total,
            "rows_per_sec": round(total / wall, 1),
            # the pre-produced backlog IS the lag: draining it to 0 is
            # the lag-drain measurement
            "lag_drain_rows": total,
            "lag_drain_s": round(wall, 3),
            "lag_final": max(int(o.get("lagFinal") or 0) for o in outs),
        }

    ladder = {}
    # single-consumer baseline: broker AND consumer confined to ONE
    # core — the single-consumer LLC ceiling as INGEST_r5 committed it
    # (a cpu_cores=1 capture).  A dedicated broker process is used so
    # the affinity is set before any serving thread spawns.
    if 1 in args.ladder_rungs:
        pin_broker, pin_port = start_broker(1, pin_core=cores[0])
        produce_all(pin_port, 1)
        ladder["c1"] = rung(pin_port, 1, pin_core=cores[0])
        pin_broker.terminate()
        print(json.dumps({"rung": ladder["c1"]}), file=sys.stderr, flush=True)
    broker_proc, port = start_broker(partitions)
    produce_all(port, partitions)
    for c in args.ladder_rungs:
        if c == 1:
            continue
        ladder[f"c{c}"] = rung(port, c)
        print(json.dumps({"rung": ladder[f"c{c}"]}), file=sys.stderr, flush=True)
    broker_proc.terminate()

    # c1 only exists when rung 1 was requested; ratios degrade to None
    single = (ladder.get("c1") or {}).get("rows_per_sec")
    best = max(r["rows_per_sec"] for r in ladder.values())
    doc = {
        "metric": "ingest_parallel_rows_per_sec",
        "value": best,
        "bench": "partition_parallel_ingest_ladder",
        "path": "RealtimeSegmentDataManager (columnar TCP fetch -> "
        "np.frombuffer decode -> vectorized dictionary encode) driven "
        "by IngestConsumerPool, one consumer process per partition",
        "platform": "cpu",
        "cpu_cores": len(cores),
        "partitions": partitions,
        "rows_per_partition": args.rows,
        "ladder": ladder,
        "single_consumer_rows_per_sec": single,
        "parallel_vs_single": round(best / single, 3) if single else None,
        "r5_single_consumer_ceiling_rows_per_sec": R5_SINGLE_CONSUMER_CEILING,
        "vs_r5_single_consumer_ceiling": round(
            best / R5_SINGLE_CONSUMER_CEILING, 3
        ),
        "note": "c1 pins broker+consumer to ONE core (the single-"
        "consumer LLC ceiling as INGEST_r5 committed it, cpu_cores=1); "
        "parallel rungs use every core.  2-core CI caveat: the "
        "vectorized dictionary encode is MEMORY-BANDWIDTH-bound on "
        "this container (two pure-encode processes with no broker "
        "measure the same ~1.3-1.4x wall), so parallel_vs_single "
        "saturates near 1.3x here — re-capture on a many-core host "
        "for the full partition-parallel curve.  vs_r5_single_"
        "consumer_ceiling is the arc's headline: aggregate ingest vs "
        "the committed INGEST_r5 single-consumer LLC ceiling.",
    }
    out = json.dumps(doc, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-partitions", type=int, default=4)
    ap.add_argument("-rows", type=int, default=1_000_000, help="rows per partition")
    ap.add_argument("-out", type=str, default="")
    ap.add_argument(
        "--ladder", action="store_true",
        help="run the r15 partition-parallel consumer ladder instead",
    )
    ap.add_argument(
        "--ladder-rungs", type=int, nargs="+", default=[1, 2, 4],
        help="consumer counts per ladder rung",
    )
    args = ap.parse_args()
    if args.ladder:
        return ladder_main(args)

    from pinot_tpu.realtime.netstream import NetworkStreamProvider

    env = dict(os.environ)
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    broker_proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "pinot_tpu.tools.ingest_bench",
            "--broker",
            str(args.partitions),
        ],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
    )
    host = "127.0.0.1"
    port = int(json.loads(broker_proc.stdout.readline())["port"])

    # pre-produce every partition (setup, not measured): one producer
    # thread per partition overlaps the JSON encode
    t0 = time.perf_counter()

    def produce(p: int) -> None:
        provider = NetworkStreamProvider(host, port, TOPIC)
        cols = gen_columns(args.rows, seed=17 + p)
        for i in range(0, args.rows, BLOCK_ROWS):
            block = {c: a[i : i + BLOCK_ROWS] for c, a in cols.items()}
            provider.produce_columns(block, partition=p)

    producers = [threading.Thread(target=produce, args=(p,)) for p in range(args.partitions)]
    for t in producers:
        t.start()
    for t in producers:
        t.join()
    produce_s = time.perf_counter() - t0
    print(json.dumps({"produce_s": round(produce_s, 1)}), flush=True)

    # solo phase FIRST (nothing else consuming): one consumer, no query
    # load — the peak per-core consumer rate (fetches are
    # offset-addressed and non-destructive, so partition 0 re-drains in
    # the parallel phase)
    solo_rows, solo_s, _ = drain_partition(host, port, 0, args.rows)
    solo_rate = round(solo_rows / solo_s, 1)
    print(json.dumps({"solo_consumer_rows_per_sec": solo_rate}), flush=True)

    # consumers: partition 0 in-process (query target), 1..N-1 as procs
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "pinot_tpu.tools.ingest_bench",
                "--worker",
                host,
                str(port),
                str(p),
                str(args.rows),
            ],
            stdout=subprocess.PIPE,
            text=True,
            env=env,
        )
        for p in range(1, args.partitions)
    ]

    # partition 0's live mutable segment exists BEFORE its consumer
    # starts, so a broker can serve it while rows stream in
    from pinot_tpu.realtime.mutable import MutableSegment
    from pinot_tpu.tools.cluster_harness import single_server_broker

    live_seg = MutableSegment(adclick_schema(), "rt0", "adclicks")
    qbroker = single_server_broker("adclicks", [live_seg])
    local: dict = {}

    def local_consume() -> None:
        total, secs, _ = drain_partition(host, port, 0, args.rows, seg=live_seg)
        local.update({"rows": total, "seconds": secs})

    t_local = threading.Thread(target=local_consume)
    t_local.start()

    # query p50/p99 measured DURING the sustained ingest window: every
    # query sees the consumer's latest snapshot watermark advance
    pql = (
        "SELECT count(*), sum(clicks) FROM adclicks "
        "GROUP BY campaign_id TOP 10"
    )
    while live_seg.num_docs == 0 and t_local.is_alive():
        time.sleep(0.02)
    for _ in range(3):
        qbroker.handle_pql(pql)  # warm + compile
    during: List[float] = []
    docs_seen: List[int] = []
    while t_local.is_alive():
        q0 = time.perf_counter()
        resp = qbroker.handle_pql(pql)
        assert not resp.exceptions, resp.exceptions
        during.append((time.perf_counter() - q0) * 1000)
        docs_seen.append(resp.num_docs_scanned)
        # ~1 QPS probe cadence: measure live-query latency without the
        # query loop itself stealing the (single) core from ingest
        time.sleep(max(0.0, 1.0 - (time.perf_counter() - q0)))
    t_local.join()

    results = [json.loads(p.communicate(timeout=600)[0].splitlines()[-1]) for p in procs]
    results.append(
        {"partition": 0, "rows": local["rows"], "seconds": round(local["seconds"], 3)}
    )
    broker_proc.terminate()

    total_rows = sum(r["rows"] for r in results)
    slowest = max(r["seconds"] for r in results)
    doc = {
        "bench": "parallel_realtime_ingest",
        "schema": "numeric-heavy (3 int/long dims, 2 numeric metrics, time)",
        "path": "columnar TCP stream fetch -> np.frombuffer decode -> "
        "vectorized dictionary encode -> commit",
        "cpu_cores": len(os.sched_getaffinity(0)),
        "partitions": args.partitions,
        "rows_per_partition": args.rows,
        "total_rows": total_rows,
        "per_consumer": results,
        "solo_consumer_rows_per_sec": solo_rate,
        "aggregate_rows_per_sec": round(total_rows / slowest, 1),
        "queries_during_ingest": len(during),
        "query_during_ingest_p50_ms": round(sorted(during)[len(during) // 2], 2) if during else None,
        "query_during_ingest_p99_ms": (
            round(sorted(during)[min(len(during) - 1, int(len(during) * 0.99))], 2)
            if during
            else None
        ),
        "docs_growing_during_queries": bool(docs_seen and docs_seen[-1] > docs_seen[0]),
    }
    out = json.dumps(doc, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--broker":
        broker_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--ladder-worker":
        ladder_worker_main()
    else:
        main()
