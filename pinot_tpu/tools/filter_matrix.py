"""Filter-tier A/B matrix: selectivity x clustering x path (r4 #3, r17).

The engine picks among four filter tiers (the reference's
Bitmap/Sorted vs Scan operator choice, ``BitmapBasedFilterOperator.java:34``
vs ``ScanBasedFilterOperator.java:38``, plus the bit-sliced range tier):

  invindex   host CSR postings, O(matches), doc-order independent
  zonemap    per-64k-block pruning + device block gather (needs
             clustered values)
  bitsliced  packed bit-plane bitwise pass, O(bit-width) planes with
             popcount-fused aggregates (engine/bitsliced.py, r17)
  fullscan   the device scan kernel, O(n)

This tool measures broker-path p50 for each (selectivity, clustering,
path) cell so the crossovers in the path-choice logic
(engine/tiercost.py) are set from data, and reports per-cell winners.
Selectivity is swept with date windows on the CLUSTERED l_shipdate
column and value sets + mid-selectivity ranges on the SHUFFLED
high-cardinality l_extendedprice column (the wide-range cells are the
bit-sliced tier's home turf: too many matches for postings, no
clustering for the zone map, and fused aggregates spare the scan).

The output document is a perf_gate kind (``metric:
"filtermatrix_<platform>"``): ``tier_wins`` counts cells won per tier
and ``bitsliced_midsel_wins`` counts shuffled mid-selectivity range
cells the bit-sliced tier wins — the committed capture is
FILTER_MATRIX_CPU_r17.json.

Usage:
  python -m pinot_tpu.tools.filter_matrix                  # bench shape
  python -m pinot_tpu.tools.filter_matrix -segments 2 -rows-per-segment 250000
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List


# label -> (PINOT_TPU_INVINDEX, PINOT_TPU_ZONEMAP, PINOT_TPU_BITSLICED)
PATHS = {
    "invindex": ("1", "0", "0"),
    "zonemap": ("0", "1", "0"),
    "bitsliced": ("0", "0", "force"),
    "fullscan": ("0", "0", "0"),
}


def _shipdate_windows(segments) -> List[tuple]:
    """(label, pql, approx_selectivity) for the clustered column."""
    d = segments[0].column("l_shipdate").dictionary
    card = d.cardinality
    vals = [d.get(i) for i in range(card)]

    def between(frac: float, label: str):
        k = max(1, int(card * frac))
        mid = card // 2
        lo, hi = vals[mid - k // 2], vals[min(mid + k // 2, card - 1)]
        return (
            label,
            f"SELECT sum(l_extendedprice), count(*) FROM lineitem "
            f"WHERE l_shipdate BETWEEN {lo!r} AND {hi!r}",
            frac,
        )

    return [
        (
            "eq_1day",
            f"SELECT sum(l_extendedprice), count(*) FROM lineitem "
            f"WHERE l_shipdate = {vals[card // 2]!r}",
            1.0 / card,
        ),
        between(0.002, "win_0.2pct"),
        between(0.01, "win_1pct"),
        between(0.05, "win_5pct"),
        between(0.25, "win_25pct"),
    ]


def _price_points(segments) -> List[tuple]:
    """(label, pql, approx_selectivity) for the shuffled column."""
    d = segments[0].column("l_extendedprice").dictionary
    card = d.cardinality
    step = max(1, card // 64)

    def in_list(k: int, label: str):
        pts = [d.get((i * step) % card) for i in range(k)]
        lst = ", ".join(repr(p) for p in pts)
        return (
            label,
            f"SELECT sum(l_quantity), count(*) FROM lineitem "
            f"WHERE l_extendedprice IN ({lst})",
            k / card,
        )

    def mid_range(frac: float, label: str):
        # dictionary is sorted; an index window of `frac` of the
        # cardinality approximates `frac` row selectivity on the
        # uniformly-drawn price column — the wide-range cells no
        # postings list or zone map helps with (r17)
        k = max(1, int(card * frac))
        mid = card // 2
        lo = d.get(max(mid - k // 2, 0))
        hi = d.get(min(mid + k // 2, card - 1))
        return (
            label,
            f"SELECT sum(l_quantity), count(*) FROM lineitem "
            f"WHERE l_extendedprice BETWEEN {lo!r} AND {hi!r}",
            frac,
        )

    return [
        (
            "eq_1val",
            f"SELECT sum(l_quantity), count(*) FROM lineitem "
            f"WHERE l_extendedprice = {d.get(card // 2)!r}",
            1.0 / card,
        ),
        in_list(8, "in_8vals"),
        in_list(16, "in_16vals"),
        mid_range(0.10, "range_10pct"),
        mid_range(0.40, "range_40pct"),
    ]


def run_matrix(segments, reps: int) -> dict:
    from pinot_tpu.tools.cluster_harness import single_server_broker
    from pinot_tpu.tools.query_runner import QueryRunner

    broker = single_server_broker("lineitem", segments)
    total_rows = sum(s.num_docs for s in segments)
    last = {}

    def run(pql: str) -> None:
        resp = broker.handle_pql(pql)
        assert not resp.exceptions, resp.exceptions
        last["entries"] = resp.num_entries_scanned_in_filter
        last["cost"] = resp.cost or {}

    runner = QueryRunner(run)
    cases = [("clustered", c) for c in _shipdate_windows(segments)] + [
        ("shuffled", c) for c in _price_points(segments)
    ]
    flags = (
        "PINOT_TPU_INVINDEX",
        "PINOT_TPU_ZONEMAP",
        "PINOT_TPU_BITSLICED",
        "PINOT_TPU_INDEX_MAX_MATCHES",
    )
    saved = {k: os.environ.get(k) for k in flags}
    cells: List[dict] = []
    try:
        for shape, (label, pql, sel) in cases:
            row: Dict[str, object] = {
                "shape": shape,
                "case": label,
                "selectivity": round(sel, 5),
            }
            for path, (inv, zm, bsi) in PATHS.items():
                os.environ["PINOT_TPU_INVINDEX"] = inv
                os.environ["PINOT_TPU_ZONEMAP"] = zm
                os.environ["PINOT_TPU_BITSLICED"] = bsi
                # invindex cells FORCE the postings path past its
                # selectivity bail so every cell measures its own path
                # (the crossover is what the matrix exists to find)
                if path == "invindex":
                    os.environ["PINOT_TPU_INDEX_MAX_MATCHES"] = str(total_rows)
                else:
                    os.environ.pop("PINOT_TPU_INDEX_MAX_MATCHES", None)
                runner.single_thread([pql], rounds=3)  # warm + compile
                r = runner.single_thread([pql] * reps, rounds=1)
                rj = r.to_json()
                row[f"{path}_p50_ms"] = rj["p50Ms"]
                row[f"{path}_p90_ms"] = rj["p90Ms"]
                row[f"{path}_entries_scanned"] = last.get("entries")
                if path == "bitsliced":
                    # "force" only skips the cost model — structurally
                    # ineligible cells (non-fusable aggs, REGEX...) fall
                    # through to the scan; the cost vector says which
                    row["bitsliced_engaged"] = bool(
                        last.get("cost", {}).get("segmentsBitsliced")
                    )
            # zonemap cannot be forced past its half-table bail: mark
            # cells where it fell through to the scan (identical
            # filter-entry count) so they are not read as zonemap wins
            row["zonemap_engaged"] = (
                row["zonemap_entries_scanned"] != row["fullscan_entries_scanned"]
            )
            row["winner"] = min(PATHS, key=lambda p: row[f"{p}_p50_ms"])
            if row["winner"] == "zonemap" and not row["zonemap_engaged"]:
                row["winner"] = "fullscan"
            if row["winner"] == "bitsliced" and not row["bitsliced_engaged"]:
                row["winner"] = "fullscan"
            cells.append(row)
            print(json.dumps(row), flush=True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    tier_wins = {p: 0 for p in PATHS}
    for row in cells:
        tier_wins[str(row["winner"])] += 1
    midsel = [
        r
        for r in cells
        if r["shape"] == "shuffled" and str(r["case"]).startswith("range_")
    ]
    return {
        "matrix": cells,
        "tier_wins": tier_wins,
        "bitsliced_midsel_wins": sum(
            1 for r in midsel if r["winner"] == "bitsliced"
        ),
        "total_rows": total_rows,
        "num_segments": len(segments),
        "reps": reps,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-segments", type=int, default=None)
    ap.add_argument("-rows-per-segment", type=int, default=None, dest="rps")
    ap.add_argument("-reps", type=int, default=15)
    ap.add_argument("-out", type=str, default="")
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    n_seg = args.segments if args.segments is not None else (16 if on_tpu else 2)
    rps = args.rps if args.rps is not None else (8_388_608 if on_tpu else 250_000)

    from pinot_tpu.tools.datagen import synthetic_lineitem_segment

    t0 = time.perf_counter()
    segments = [
        synthetic_lineitem_segment(rps, seed=11 + i, name=f"li{i}")
        for i in range(n_seg)
    ]
    print(json.dumps({"datagen_s": round(time.perf_counter() - t0, 1)}), flush=True)
    doc = run_matrix(segments, args.reps)
    doc["platform"] = jax.devices()[0].platform
    doc["metric"] = f"filtermatrix_{doc['platform']}"
    doc["value"] = doc["bitsliced_midsel_wins"]
    text = json.dumps(doc, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
