"""Backend forcing for virtual-CPU-mesh validation runs.

Multi-chip sharding is validated on a virtual CPU device mesh
(``--xla_force_host_platform_device_count``) because only one real TPU
chip is reachable (SURVEY §7 stage 4; the driver's ``dryrun_multichip``
contract). The container's sitecustomize force-sets
``JAX_PLATFORMS=axon`` before any user code runs, so plain env vars
from a caller are not enough — the jax *config* must be updated before
the first backend initialization.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_mesh(n_devices: int) -> bool:
    """Arrange for jax to come up on the CPU platform with at least
    ``n_devices`` virtual devices.

    Must run before the first jax backend initialization in this
    process. Returns True if the platform config was (or already is)
    CPU-forcible; False if backends already initialized on another
    platform (too late — the caller should fail with a clear message).
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        # Too late to change platform or device count; don't touch the
        # env either (subprocesses should inherit the true state).
        return jax.default_backend() == "cpu" and len(jax.devices()) >= n_devices

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{re.escape(_COUNT_FLAG)}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = re.sub(
            rf"{re.escape(_COUNT_FLAG)}=\d+", f"{_COUNT_FLAG}={n_devices}", flags
        )
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The axon factory stays *registered* (pallas + mlir need the platform
    # names known); this only keeps its PJRT client from being dialed.
    jax.config.update("jax_platforms", "cpu")
    return True


def probe_device(timeout_s: float = 120.0) -> bool:
    """True when the default backend initializes in a SUBPROCESS within
    the timeout.  The axon tunnel can wedge so hard that the first
    device op blocks forever in-process; probing out-of-process keeps
    the caller clean to fall back to CPU (bench.py and
    ``__graft_entry__.entry`` both gate on this)."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False
