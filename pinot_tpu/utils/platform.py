"""Backend forcing for virtual-CPU-mesh validation runs.

Multi-chip sharding is validated on a virtual CPU device mesh
(``--xla_force_host_platform_device_count``) because only one real TPU
chip is reachable (SURVEY §7 stage 4; the driver's ``dryrun_multichip``
contract). The container's sitecustomize force-sets
``JAX_PLATFORMS=axon`` before any user code runs, so plain env vars
from a caller are not enough — the jax *config* must be updated before
the first backend initialization.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_mesh(n_devices: int) -> bool:
    """Arrange for jax to come up on the CPU platform with at least
    ``n_devices`` virtual devices.

    Must run before the first jax backend initialization in this
    process. Returns True if the platform config was (or already is)
    CPU-forcible; False if backends already initialized on another
    platform (too late — the caller should fail with a clear message).
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        # Too late to change platform or device count; don't touch the
        # env either (subprocesses should inherit the true state).
        return jax.default_backend() == "cpu" and len(jax.devices()) >= n_devices

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{re.escape(_COUNT_FLAG)}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = re.sub(
            rf"{re.escape(_COUNT_FLAG)}=\d+", f"{_COUNT_FLAG}={n_devices}", flags
        )
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The axon factory stays *registered* (pallas + mlir need the platform
    # names known); this only keeps its PJRT client from being dialed.
    jax.config.update("jax_platforms", "cpu")
    return True


# ---------------------------------------------------------------------------
# Declared per-platform roofline peaks.
#
# The utilization plane divides MEASURED achieved FLOP/s and bytes/s by
# these DECLARED peaks to get a roofline fraction (PAPERS.md's
# bulk-bitwise PIM line argues from exactly this achieved-vs-peak
# framing).  Values are per-chip datasheet numbers: dense bf16/fp
# peak FLOP/s and HBM bandwidth.  Matching is by ``device_kind``
# substring (longest match wins) so "TPU v5 lite" and "TPU v5e" both
# land on the v5e row.  Unknown platforms (CPU test runs, new chips)
# report None peaks — the roofline fraction is then "unavailable", not
# a made-up number — unless the operator declares peaks via
# ``PINOT_TPU_PEAK_FLOPS`` / ``PINOT_TPU_PEAK_HBM_BPS``.
# ---------------------------------------------------------------------------

# lowercase device_kind substring -> (peak FLOP/s, peak HBM bytes/s)
_PLATFORM_PEAKS = {
    "v5 lite": (197e12, 819e9),  # v5e: 197 TFLOP/s bf16, 819 GB/s
    "v5litepod": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v6e": (918e12, 1640e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
}

_peaks_cache = None


def platform_peaks(refresh: bool = False) -> dict:
    """Declared roofline peaks for this process's default device.

    Returns ``{"platform", "deviceKind", "peakFlopsPerSec",
    "peakBytesPerSec", "source"}``.  Peaks are None when the platform
    is unknown (source "unknown") or when jax backends have not
    initialized yet (source "uninitialized" — this function must NEVER
    trigger backend init: on a wedged device tunnel ``jax.devices()``
    blocks forever, and metric scrapes call through here).  Env
    overrides (``PINOT_TPU_PEAK_FLOPS`` / ``PINOT_TPU_PEAK_HBM_BPS``,
    source "env") win over the table — the CPU escape hatch and the
    knob for chips the table doesn't know."""
    global _peaks_cache
    env_flops = os.environ.get("PINOT_TPU_PEAK_FLOPS")
    env_bps = os.environ.get("PINOT_TPU_PEAK_HBM_BPS")
    if not refresh and _peaks_cache is not None and not (env_flops or env_bps):
        return dict(_peaks_cache)
    out = {
        "platform": None,
        "deviceKind": None,
        "peakFlopsPerSec": None,
        "peakBytesPerSec": None,
        "source": "unknown",
    }
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            out["source"] = "uninitialized"
        else:
            import jax

            dev = jax.devices()[0]
            out["platform"] = dev.platform
            kind = (getattr(dev, "device_kind", "") or "").lower()
            out["deviceKind"] = kind
            best = None
            for sub, peaks in _PLATFORM_PEAKS.items():
                if sub in kind and (best is None or len(sub) > len(best[0])):
                    best = (sub, peaks)
            if best is not None:
                out["peakFlopsPerSec"], out["peakBytesPerSec"] = best[1]
                out["source"] = "declared"
    except Exception:
        out["source"] = "error"
    if env_flops or env_bps:
        try:
            # parse BOTH before applying EITHER: a half-applied pair
            # would report one env peak under a non-"env" source label
            parsed_flops = float(env_flops) if env_flops else None
            parsed_bps = float(env_bps) if env_bps else None
        except ValueError:
            pass  # junk overrides must not break metric scrapes
        else:
            if parsed_flops is not None:
                out["peakFlopsPerSec"] = parsed_flops
            if parsed_bps is not None:
                out["peakBytesPerSec"] = parsed_bps
            out["source"] = "env"
    # never cache transient states: "uninitialized" resolves once a
    # backend comes up, and "error" may be a one-off probe hiccup — a
    # pinned error would report None peaks on a known TPU until restart
    if out["source"] not in ("uninitialized", "error") and not (
        env_flops or env_bps
    ):
        _peaks_cache = dict(out)
    return out


def roofline_fractions(
    achieved_bytes_per_sec,
    achieved_flops_per_sec=None,
    peaks: "dict | None" = None,
) -> dict:
    """Per-resource achieved-vs-peak fractions — the ONE place the
    roofline verdict rule lives (PlanStatsStore per-shape entries and
    the server-wide recent window both call through here).

    Returns ``{"bandwidthFraction"?, "flopsFraction"?,
    "rooflineFraction"}``: a per-resource key is present only when its
    peak is declared AND the achieved rate is positive; a kernel is "at
    the roofline" when its BEST-utilized resource is, so
    ``rooflineFraction`` is the max of the present fractions — or the
    explicit None (never an invented 0) when no peak is declared."""
    if peaks is None:
        peaks = platform_peaks()
    out: dict = {}
    fractions = []
    if peaks.get("peakBytesPerSec") and achieved_bytes_per_sec:
        f = achieved_bytes_per_sec / peaks["peakBytesPerSec"]
        out["bandwidthFraction"] = round(f, 6)
        fractions.append(f)
    if peaks.get("peakFlopsPerSec") and achieved_flops_per_sec:
        f = achieved_flops_per_sec / peaks["peakFlopsPerSec"]
        out["flopsFraction"] = round(f, 6)
        fractions.append(f)
    out["rooflineFraction"] = round(max(fractions), 6) if fractions else None
    return out


def probe_device(timeout_s: float = 120.0) -> bool:
    """True when the default backend initializes in a SUBPROCESS within
    the timeout.  The axon tunnel can wedge so hard that the first
    device op blocks forever in-process; probing out-of-process keeps
    the caller clean to fall back to CPU (bench.py and
    ``__graft_entry__.entry`` both gate on this)."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False
