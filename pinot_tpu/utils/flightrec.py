"""Flight recorder: bounded on-disk postmortem bundles on notable events.

When something notable happens — an SLO burn crossing, a shed burst, a
heal event, a dead server — the in-memory observability state that
explains it (the history window, the slow-query log, the retained tail
traces, plan stats, device snapshots) is exactly what gets lost when
the operator arrives an hour later, or when the process restarts.  The
flight recorder dumps that state to disk AT the event:

- one JSON file per bundle (``frec-<millis>-<role>-<name>-<reason>.json``,
  written atomically via tmp+rename), each a ``{"reason", "ts",
  "sources": {...}}`` document whose sources are the role's own debug
  snapshots;
- bounded like the PR 10 profiler captures: oldest bundles pruned
  BEFORE a new one is written (``PINOT_TPU_FLIGHTREC_MAX``, default 8);
- rate-limited (``PINOT_TPU_FLIGHTREC_MIN_INTERVAL_S``, default 30s
  between dumps) so a failure storm costs one bundle, not a disk full;
- **disabled unless ``PINOT_TPU_FLIGHTREC_DIR`` is set** (or a dir is
  passed explicitly) — tests and benches opt in.

Triggers are role-owned hooks on the HistoryRecorder cadence (broker:
SLO burn crossing / shed burst / failed query; server: heal events;
controller: dead servers / stabilizer repairs) — see each role's
``_history_tick``.  ``tools/doctor.py`` collects every role's bundles
plus live debug endpoints into one cluster-wide postmortem.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlightRecorder:
    def __init__(
        self,
        role: str,
        name: str,
        sources: Optional[Dict[str, Callable[[], Any]]] = None,
        directory: Optional[str] = None,
        max_bundles: Optional[int] = None,
        min_interval_s: Optional[float] = None,
        metrics=None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.role = role
        self.name = name
        self.dir = directory if directory is not None else (
            os.environ.get("PINOT_TPU_FLIGHTREC_DIR") or None
        )
        self.max_bundles = int(
            _env_f("PINOT_TPU_FLIGHTREC_MAX", 8)
            if max_bundles is None
            else max_bundles
        )
        self.min_interval_s = (
            _env_f("PINOT_TPU_FLIGHTREC_MIN_INTERVAL_S", 30.0)
            if min_interval_s is None
            else min_interval_s
        )
        self._sources: Dict[str, Callable[[], Any]] = dict(sources or {})
        self._clock = clock
        self._last_dump = 0.0
        self._seq = 0
        self._lock = threading.Lock()
        self.metrics = metrics
        if metrics is not None:
            metrics.meter("flightrec.dumps")
            metrics.gauge("flightrec.bundles").set_fn(
                lambda: len(self.bundle_files())
            )

    @property
    def enabled(self) -> bool:
        return bool(self.dir)

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        self._sources[name] = fn

    # -- disk side -----------------------------------------------------
    def bundle_files(self) -> List[str]:
        """Absolute paths of THIS recorder's bundles, oldest first (the
        filename's millisecond stamp + sequence orders them)."""
        if not self.dir or not os.path.isdir(self.dir):
            return []
        prefix = f"frec-"
        mine = f"-{self.role}-{self.name}-"
        out = [
            os.path.join(self.dir, f)
            for f in os.listdir(self.dir)
            if f.startswith(prefix) and mine in f and f.endswith(".json")
        ]
        return sorted(out)

    def _prune(self) -> None:
        files = self.bundle_files()
        # prune BEFORE writing (the profiler lesson: pruning after with
        # max_bundles=1 deletes the bundle just written)
        while len(files) >= max(1, self.max_bundles):
            victim = files.pop(0)
            try:
                os.remove(victim)
            except OSError:
                pass

    def maybe_dump(
        self, reason: str, detail: Optional[Dict[str, Any]] = None
    ) -> Optional[str]:
        """Collect every source and write one bundle, unless disabled or
        inside the rate-limit window.  Source failures degrade to an
        ``{"error": ...}`` entry — a sick snapshot never loses the rest
        of the bundle.  Returns the written path (or None)."""
        if not self.enabled:
            return None
        now = self._clock()
        with self._lock:
            if now - self._last_dump < self.min_interval_s:
                return None
            prev_last = self._last_dump
            self._last_dump = now
            self._seq += 1
            seq = self._seq
        bundle: Dict[str, Any] = {
            "role": self.role,
            "instance": self.name,
            "reason": reason,
            "ts": round(now, 3),
            "detail": detail or {},
            "sources": {},
        }
        for sname, fn in self._sources.items():
            try:
                bundle["sources"][sname] = fn()
            except Exception as e:
                bundle["sources"][sname] = {"error": f"{type(e).__name__}: {e}"}
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._prune()
            fname = (
                f"frec-{int(now * 1000)}-{self.role}-{self.name}-{reason}-{seq}.json"
            )
            path = os.path.join(self.dir, fname)
            tmp = path + ".part"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f)
            os.replace(tmp, path)
        except OSError:
            logger.warning("flight-recorder dump failed", exc_info=True)
            with self._lock:
                # no bundle exists: give the window back so the NEXT
                # notable event isn't silently dropped for min_interval_s
                if self._last_dump == now:
                    self._last_dump = prev_last
            return None
        if self.metrics is not None:
            self.metrics.meter("flightrec.dumps").mark()
        logger.warning(
            "flight-recorder bundle written: %s (%s)", path, reason
        )
        return path

    # -- read side -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """``/debug/flightrec`` payload: config + bundle inventory."""
        bundles = []
        for path in self.bundle_files():
            try:
                st = os.stat(path)
                bundles.append(
                    {
                        "file": os.path.basename(path),
                        "bytes": st.st_size,
                        "mtime": round(st.st_mtime, 3),
                    }
                )
            except OSError:
                continue
        return {
            "enabled": self.enabled,
            "dir": self.dir,
            "maxBundles": self.max_bundles,
            "minIntervalS": self.min_interval_s,
            "dumps": 0
            if self.metrics is None
            else self.metrics.meter("flightrec.dumps").count,
            "bundles": bundles,
        }
