"""Per-table SLOs evaluated as multi-window burn rates over history.

Objectives (per table, table-config ``slo`` block with env defaults):

- **latency**: fraction of queries answering under ``latencyMs`` must
  stay >= ``latencyTarget`` (default 99% under 500ms).
- **availability**: fraction of queries answering WITHOUT exceptions
  (sheds included — a 429 is client-visible unavailability) must stay
  >= ``availabilityTarget`` (default 99.9%).

Burn rate is the standard error-budget formulation: over a window W,

    burn(W) = bad_fraction(W) / (1 - target)

1.0 means the budget burns exactly at the sustainable rate; 10 means
the monthly budget is gone in ~3 days.  Following the multi-window
practice, a table is **burning** only when BOTH the fast (default 5m)
and slow (default 1h) windows exceed ``PINOT_TPU_SLO_BURN_THRESHOLD``
(default 1.0) — a fast-window spike alone (one slow query after an
idle hour) does not page.

The window math rides the ``HistoryRecorder`` ring (utils/timeseries.py)
— the tracker publishes cumulative per-table counters as history series
(``slo.<table>.total/latencyBreaches/failures``) and the burn rates are
windowed deltas of those, so ``/debug/history`` and ``/debug/slo``
can never disagree about what happened.

Env knobs: ``PINOT_TPU_SLO_LATENCY_MS`` (500), ``PINOT_TPU_SLO_LATENCY_TARGET``
(0.99), ``PINOT_TPU_SLO_AVAILABILITY_TARGET`` (0.999),
``PINOT_TPU_SLO_FAST_WINDOW_S`` (300), ``PINOT_TPU_SLO_SLOW_WINDOW_S``
(3600), ``PINOT_TPU_SLO_BURN_THRESHOLD`` (1.0).  The reported field
names stay ``burnRate5m`` / ``burnRate1h`` whatever the windows are
tuned to (chaos tests shrink them to seconds).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_objective() -> Dict[str, float]:
    return {
        "latencyMs": _env_f("PINOT_TPU_SLO_LATENCY_MS", 500.0),
        "latencyTarget": _env_f("PINOT_TPU_SLO_LATENCY_TARGET", 0.99),
        "availabilityTarget": _env_f("PINOT_TPU_SLO_AVAILABILITY_TARGET", 0.999),
        # event-time freshness objective (ISSUE 19): fraction of
        # realtime-serving queries with freshnessMs under the threshold
        # must stay >= freshnessTarget.  Threshold 0 (the default)
        # disables the objective — its budget contributes no burn entry
        # (the _burn budget<=0 guard), so pure-offline fleets see no
        # behavior change.
        "freshnessMs": _env_f("PINOT_TPU_SLO_FRESHNESS_MS", 0.0),
        "freshnessTarget": _env_f("PINOT_TPU_SLO_FRESHNESS_TARGET", 0.99),
    }


class _Counts:
    __slots__ = ("total", "latency_breaches", "failures", "freshness_breaches")

    def __init__(self) -> None:
        self.total = 0
        self.latency_breaches = 0
        self.failures = 0
        self.freshness_breaches = 0


class SloTracker:
    """Broker-side per-table SLO state: cumulative counters fed per
    query, objectives fed from table config, burn rates evaluated over
    the bound ``HistoryRecorder``."""

    def __init__(
        self,
        history=None,
        metrics=None,
        fast_window_s: Optional[float] = None,
        slow_window_s: Optional[float] = None,
        burn_threshold: Optional[float] = None,
    ) -> None:
        self.history = history
        self.metrics = metrics
        self.fast_window_s = (
            _env_f("PINOT_TPU_SLO_FAST_WINDOW_S", 300.0)
            if fast_window_s is None
            else fast_window_s
        )
        self.slow_window_s = (
            _env_f("PINOT_TPU_SLO_SLOW_WINDOW_S", 3600.0)
            if slow_window_s is None
            else slow_window_s
        )
        self.burn_threshold = (
            _env_f("PINOT_TPU_SLO_BURN_THRESHOLD", 1.0)
            if burn_threshold is None
            else burn_threshold
        )
        self._counts: Dict[str, _Counts] = {}
        self._objectives: Dict[str, Dict[str, float]] = {}  # table overrides
        # env defaults resolved ONCE: observe() runs on the broker's
        # per-query response path and must not re-read os.environ
        self._default_obj = default_objective()
        self._burning: set = set()
        self._lock = threading.Lock()
        if metrics is not None:
            # pre-registered so /metrics shows zeros before first use
            metrics.gauge("slo.burning").set(0)
            metrics.gauge("slo.worstBurnRate5m").set(0.0)
            metrics.gauge("slo.worstBurnRate1h").set(0.0)

    # -- write side ----------------------------------------------------
    def observe(
        self,
        table: str,
        latency_ms: float,
        failed: bool,
        freshness_ms: Optional[float] = None,
    ) -> None:
        """Fold one finished query into the table's cumulative counters
        (called on the broker response path — scalars only).
        ``freshness_ms`` is the response's event-time staleness (None
        for offline-only answers, which never breach freshness)."""
        if not table:
            return
        with self._lock:
            obj = self._objectives.get(table) or self._default_obj
            c = self._counts.get(table)
            if c is None:
                c = self._counts[table] = _Counts()
            c.total += 1
            if failed:
                c.failures += 1
                # a failed query never answered under the latency bar
                c.latency_breaches += 1
            elif latency_ms >= obj["latencyMs"]:
                c.latency_breaches += 1
            threshold = obj.get("freshnessMs") or 0.0
            if (
                threshold > 0
                and freshness_ms is not None
                and freshness_ms >= threshold
            ):
                c.freshness_breaches += 1

    def set_objective(self, table: str, obj: Optional[Dict[str, Any]]) -> None:
        """Table-config override (None clears back to env defaults).
        Unset fields inside a present block fall back per-field."""
        with self._lock:
            if not obj:
                self._objectives.pop(table, None)
                return
            base = self._default_obj
            self._objectives[table] = {
                "latencyMs": float(obj.get("latencyMs") or base["latencyMs"]),
                "latencyTarget": float(
                    obj.get("latencyTarget") or base["latencyTarget"]
                ),
                "availabilityTarget": float(
                    obj.get("availabilityTarget") or base["availabilityTarget"]
                ),
                "freshnessMs": float(
                    obj.get("freshnessMs") or base["freshnessMs"]
                ),
                "freshnessTarget": float(
                    obj.get("freshnessTarget") or base["freshnessTarget"]
                ),
            }

    def objective(self, table: str) -> Dict[str, float]:
        with self._lock:
            obj = self._objectives.get(table)
        return dict(obj) if obj is not None else dict(self._default_obj)

    def objective_tables(self) -> List[str]:
        """Tables holding a config override — the propagation paths use
        this to clear objectives of tables that left the cluster state
        (a table with an ``slo`` block but no QPS quota has no quota
        bucket to piggyback stale-clearing on)."""
        with self._lock:
            return list(self._objectives)

    # -- history feed --------------------------------------------------
    def series(self) -> Dict[str, float]:
        """Cumulative per-table counters as flat history series — the
        provider registered on the role's HistoryRecorder."""
        with self._lock:
            out: Dict[str, float] = {}
            for table, c in self._counts.items():
                out[f"slo.{table}.total"] = c.total
                out[f"slo.{table}.latencyBreaches"] = c.latency_breaches
                out[f"slo.{table}.failures"] = c.failures
                out[f"slo.{table}.freshnessBreaches"] = c.freshness_breaches
            return out

    # -- evaluation ----------------------------------------------------
    def _burn(
        self, table: str, bad_series: str, budget: float, window_s: float
    ) -> Optional[Dict[str, Any]]:
        if self.history is None or budget <= 0:
            return None
        total = self.history.window_delta(f"slo.{table}.total", window_s)
        bad = self.history.window_delta(f"slo.{table}.{bad_series}", window_s)
        if total is None or bad is None or total[0] <= 0:
            return None
        frac = max(0.0, bad[0]) / total[0]
        return {
            "windowS": round(total[1], 3),
            "queries": int(total[0]),
            "bad": int(max(0.0, bad[0])),
            "badFraction": round(frac, 6),
            "burnRate": round(frac / budget, 3),
        }

    def evaluate(self, consume_crossings: bool = True) -> Dict[str, Any]:
        """Burn rates for every observed table over both windows; updates
        the slo.* gauges and returns the snapshot plus the set of tables
        that CROSSED into burning since the last evaluation (the flight-
        recorder trigger).  ``consume_crossings=False`` (the read-only
        ``snapshot()`` path: /debug/slo, fleet rollups, flight-recorder
        sources) leaves the edge state untouched — a poll between two
        history ticks must not eat the crossing the sloBurn trigger
        fires on."""
        with self._lock:
            tables = list(self._counts.keys())
        results: Dict[str, Any] = {}
        worst5 = 0.0
        worst1h = 0.0
        burning: List[str] = []
        for table in tables:
            obj = self.objective(table)
            lat_budget = 1.0 - obj["latencyTarget"]
            avail_budget = 1.0 - obj["availabilityTarget"]
            # the third objective rides the same multi-window machinery:
            # a zero threshold zeroes the budget, and the _burn guard
            # then contributes no entry at all
            fresh_budget = (
                1.0 - obj.get("freshnessTarget", 0.99)
                if (obj.get("freshnessMs") or 0.0) > 0
                else 0.0
            )
            entry: Dict[str, Any] = {"objective": obj, "windows": {}}
            rates5: List[float] = []
            rates1h: List[float] = []
            for wname, window_s, sink in (
                ("burnRate5m", self.fast_window_s, rates5),
                ("burnRate1h", self.slow_window_s, rates1h),
            ):
                lat = self._burn(table, "latencyBreaches", lat_budget, window_s)
                avail = self._burn(table, "failures", avail_budget, window_s)
                fresh = self._burn(
                    table, "freshnessBreaches", fresh_budget, window_s
                )
                entry["windows"][wname] = {
                    "latency": lat,
                    "availability": avail,
                    "freshness": fresh,
                }
                for b in (lat, avail, fresh):
                    if b is not None:
                        sink.append(b["burnRate"])
            b5 = max(rates5, default=0.0)
            b1h = max(rates1h, default=0.0)
            entry["burnRate5m"] = b5
            entry["burnRate1h"] = b1h
            entry["burning"] = (
                b5 >= self.burn_threshold and b1h >= self.burn_threshold
            )
            if entry["burning"]:
                burning.append(table)
            worst5 = max(worst5, b5)
            worst1h = max(worst1h, b1h)
            results[table] = entry
        with self._lock:
            crossed = [t for t in burning if t not in self._burning]
            if consume_crossings:
                self._burning = set(burning)
        if self.metrics is not None:
            self.metrics.gauge("slo.burning").set(len(burning))
            self.metrics.gauge("slo.worstBurnRate5m").set(round(worst5, 3))
            self.metrics.gauge("slo.worstBurnRate1h").set(round(worst1h, 3))
        # worst-burning tables first: the fleet rollup and the dashboard
        # lead with the table an operator should look at
        ranked = sorted(
            results.items(),
            key=lambda kv: -max(kv[1]["burnRate5m"], kv[1]["burnRate1h"]),
        )
        return {
            "config": {
                "fastWindowS": self.fast_window_s,
                "slowWindowS": self.slow_window_s,
                "burnThreshold": self.burn_threshold,
                "defaults": dict(self._default_obj),
            },
            "tables": dict(results),
            "burningTables": sorted(burning),
            "worstBurning": [t for t, _ in ranked[:10]],
            "crossed": crossed,
        }

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/slo`` payload (evaluation is cheap: a few windowed
        deltas per observed table).  Read-only: never consumes the
        crossing edge the flight-recorder trigger depends on."""
        out = self.evaluate(consume_crossings=False)
        out.pop("crossed", None)
        return out
