"""Metrics: meters, timers, gauges per role.

The Yammer-metrics analog (pinot-common
``common/metrics/AbstractMetrics.java`` with ``BrokerMeter``,
``ServerMeter``, ``ServerQueryPhase`` etc.): typed registries per role,
timers keep recent samples for percentile queries (the
``AggregatedHistogram`` role), everything thread-safe and cheap.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional


class Meter:
    def __init__(self) -> None:
        self.count = 0
        self._t0 = time.time()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n

    @property
    def rate(self) -> float:
        dt = time.time() - self._t0
        return self.count / dt if dt > 0 else 0.0


class Timer:
    def __init__(self, window: int = 4096) -> None:
        self.count = 0
        self.total_ms = 0.0
        self._samples: Deque[float] = deque(maxlen=window)
        self._lock = threading.Lock()

    def update(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms
            self._samples.append(ms)

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
            idx = min(int(len(s) * p / 100.0), len(s) - 1)
            return s[idx]

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0


class Gauge:
    def __init__(self) -> None:
        self.value: Any = 0

    def set(self, v: Any) -> None:
        self.value = v


class MetricsRegistry:
    """Per-role metrics registry (AbstractMetrics analog)."""

    def __init__(self, scope: str) -> None:
        self.scope = scope
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def meter(self, name: str) -> Meter:
        with self._lock:
            m = self._meters.get(name)
            if m is None:
                m = self._meters[name] = Meter()
            return m

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "scope": self.scope,
                "meters": {k: {"count": m.count, "rate": round(m.rate, 3)} for k, m in self._meters.items()},
                "timers": {
                    k: {
                        "count": t.count,
                        "meanMs": round(t.mean_ms, 3),
                        "p95Ms": round(t.percentile(95), 3),
                        "p99Ms": round(t.percentile(99), 3),
                    }
                    for k, t in self._timers.items()
                },
                "gauges": {k: g.value for k, g in self._gauges.items()},
            }


class ServerMetrics(MetricsRegistry):
    """ServerMeter/ServerTimer/ServerQueryPhase namespace."""


class BrokerMetrics(MetricsRegistry):
    """BrokerMeter/BrokerQueryPhase namespace."""


class ControllerMetrics(MetricsRegistry):
    """ControllerMeter/ControllerGauge namespace."""
