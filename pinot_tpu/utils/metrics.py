"""Metrics: meters, timers, gauges per role + Prometheus exposition.

The Yammer-metrics analog (pinot-common
``common/metrics/AbstractMetrics.java`` with ``BrokerMeter``,
``ServerMeter``, ``ServerQueryPhase`` etc.): typed registries per role,
timers keep recent samples for percentile queries (the
``AggregatedHistogram`` role), everything thread-safe and cheap.

Beyond the seed version:

- ``Meter`` keeps a 1-minute EWMA rate (5s ticks, the Yammer
  ``EWMA.oneMinuteEWMA`` scheme) next to the lifetime average — a meter
  marked heavily an hour ago no longer reports a misleading "rate".
- ``Timer.percentile`` interpolates between ranks and caches the sorted
  window (invalidated on update) instead of re-sorting the full window
  under the lock on every call; ``snapshot`` reads all percentiles from
  one sort.
- ``Gauge`` reads/writes under a lock and supports callable providers
  (``set_fn``) for live values.
- ``prometheus_text`` renders one or more registries in the Prometheus
  text exposition format (served at ``/metrics`` on the broker, server,
  and controller HTTP surfaces).
- Per-role metric-name CATALOGS are the single source of truth for
  series names; ``tools/metrics_lint.py`` asserts every name used in
  the codebase appears here, so a typo cannot silently fork a series.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Sequence

_EWMA_TICK_S = 5.0
_EWMA_ALPHA_1M = 1.0 - math.exp(-_EWMA_TICK_S / 60.0)


def interpolated_percentile(s: Sequence[float], p: float) -> float:
    """Linear-interpolated percentile over a SORTED sample sequence —
    shared by Timer and the plan-stats registry (utils/planstats.py) so
    /metrics and /debug/plans percentiles can never drift apart."""
    if not s:
        return 0.0
    if len(s) == 1:
        return s[0]
    rank = (len(s) - 1) * min(max(p, 0.0), 100.0) / 100.0
    lo = int(rank)
    frac = rank - lo
    if lo + 1 >= len(s):
        return s[-1]
    return s[lo] + frac * (s[lo + 1] - s[lo])


class Meter:
    def __init__(self) -> None:
        self.count = 0
        self._t0 = time.time()
        self._lock = threading.Lock()
        # 1-minute EWMA state (Yammer Meter semantics): marks accumulate
        # in _uncounted; every 5s tick folds them into the decayed rate
        self._uncounted = 0
        self._ewma = 0.0  # events per second
        self._ewma_init = False
        self._last_tick = time.monotonic()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._tick_locked(time.monotonic())
            self.count += n
            self._uncounted += n

    def _tick_locked(self, now: float) -> None:
        elapsed = now - self._last_tick
        if elapsed < _EWMA_TICK_S:
            return
        ticks = int(elapsed // _EWMA_TICK_S)
        # first tick consumes the accumulated marks; the rest decay
        instant = self._uncounted / _EWMA_TICK_S
        self._uncounted = 0
        if not self._ewma_init:
            self._ewma = instant
            self._ewma_init = True
            ticks -= 1
        else:
            self._ewma += _EWMA_ALPHA_1M * (instant - self._ewma)
            ticks -= 1
        for _ in range(min(ticks, 64)):  # cap idle catch-up work
            self._ewma += _EWMA_ALPHA_1M * (0.0 - self._ewma)
        if ticks > 64:
            self._ewma = 0.0
        self._last_tick += (int(elapsed // _EWMA_TICK_S)) * _EWMA_TICK_S

    @property
    def rate(self) -> float:
        """Lifetime average events/second (process-age denominator)."""
        dt = time.time() - self._t0
        return self.count / dt if dt > 0 else 0.0

    @property
    def rate_1m(self) -> float:
        """1-minute EWMA events/second — the windowed rate that tracks
        what the meter is doing NOW, not since process start."""
        with self._lock:
            self._tick_locked(time.monotonic())
            if not self._ewma_init:
                # under one tick of life: instantaneous average so short
                # tests/bursts still see a sane number
                dt = time.monotonic() - self._last_tick
                return self._uncounted / dt if dt > 0 else 0.0
            return self._ewma


class Timer:
    def __init__(self, window: int = 4096) -> None:
        self.count = 0
        self.total_ms = 0.0
        self._samples: Deque[float] = deque(maxlen=window)
        self._sorted: Optional[List[float]] = None  # cache, dropped on update
        self._lock = threading.Lock()

    def update(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += ms
            self._samples.append(ms)
            self._sorted = None

    def _sorted_locked(self) -> List[float]:
        if self._sorted is None:
            self._sorted = sorted(self._samples)
        return self._sorted

    # the ONE percentile implementation (module level above): timers
    # and the plan-stats registry must never drift apart
    _interp = staticmethod(interpolated_percentile)

    def percentile(self, p: float) -> float:
        with self._lock:
            return self._interp(self._sorted_locked(), p)

    def percentiles(self, ps: Iterable[float]) -> List[float]:
        """All requested percentiles from ONE cached sort/lock hold."""
        with self._lock:
            s = self._sorted_locked()
            return [self._interp(s, p) for p in ps]

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0


class Gauge:
    def __init__(self) -> None:
        self._value: Any = 0
        self._fn = None
        self._lock = threading.Lock()

    def set(self, v: Any) -> None:
        with self._lock:
            self._value = v
            self._fn = None

    def set_fn(self, fn) -> None:
        """Callable provider: the gauge reads live on every snapshot."""
        with self._lock:
            self._fn = fn

    def clear_fn(self, fn) -> None:
        """Detach a provider IF it is still the attached one (resets the
        gauge to 0).  The equality guard makes detach safe against a
        successor that already replaced the provider: last writer wins,
        a stale owner's detach is a no-op."""
        with self._lock:
            if self._fn == fn:
                self._fn = None
                self._value = 0

    @property
    def value(self) -> Any:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return fn()
        except Exception:
            return None


class MetricsRegistry:
    """Per-role metrics registry (AbstractMetrics analog)."""

    role = ""  # catalog key; set by typed subclasses

    def __init__(self, scope: str) -> None:
        self.scope = scope
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def meter(self, name: str) -> Meter:
        with self._lock:
            m = self._meters.get(name)
            if m is None:
                m = self._meters[name] = Meter()
            return m

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            meters = dict(self._meters)
            timers = dict(self._timers)
            gauges = dict(self._gauges)
        out: Dict[str, Any] = {
            "scope": self.scope,
            "meters": {
                k: {
                    "count": m.count,
                    "rate": round(m.rate, 3),
                    "rate1m": round(m.rate_1m, 3),
                }
                for k, m in meters.items()
            },
            "timers": {},
            "gauges": {k: g.value for k, g in gauges.items()},
        }
        for k, t in timers.items():
            p50, p95, p99 = t.percentiles((50, 95, 99))
            out["timers"][k] = {
                "count": t.count,
                "meanMs": round(t.mean_ms, 3),
                "p50Ms": round(p50, 3),
                "p95Ms": round(p95, 3),
                "p99Ms": round(p99, 3),
            }
        return out


class ServerMetrics(MetricsRegistry):
    """ServerMeter/ServerTimer/ServerQueryPhase namespace."""

    role = "server"


class BrokerMetrics(MetricsRegistry):
    """BrokerMeter/BrokerQueryPhase namespace."""

    role = "broker"


class ControllerMetrics(MetricsRegistry):
    """ControllerMeter/ControllerGauge namespace."""

    role = "controller"


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    """Metric name -> legal Prometheus name component."""
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _prom_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_value(v: Any) -> Optional[str]:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            return str(v)
        return repr(float(v)) if isinstance(v, float) else str(v)
    return None  # non-numeric gauges are skipped in the exposition


def prometheus_text(registries, prefix: str = "pinot_tpu") -> str:
    """Render registries as Prometheus text format 0.0.4.

    Meters -> ``<prefix>_<role>_<name>_total`` counters (plus a
    ``..._rate1m`` gauge), timers -> summary-style ``..._ms`` families
    (``_count``/``_sum`` + quantile series), gauges -> gauges.  The
    registry scope rides as the ``scope`` label so multiple instances
    of a role can share one scrape."""
    if isinstance(registries, MetricsRegistry):
        registries = [registries]
    lines: List[str] = []
    typed: set = set()

    def _family(name: str, kind: str, help_text: str = "") -> None:
        if name in typed:
            return
        typed.add(name)
        if help_text:
            lines.append(f"# HELP {name} {_prom_label(help_text)}")
        lines.append(f"# TYPE {name} {kind}")

    for reg in registries:
        role = reg.role or "generic"
        catalog = METRIC_CATALOGS.get(role, {})
        base = f"{prefix}_{_prom_name(role)}"
        label = f'{{scope="{_prom_label(reg.scope)}"}}'
        snap_lock = reg._lock
        with snap_lock:
            meters = dict(reg._meters)
            timers = dict(reg._timers)
            gauges = dict(reg._gauges)
        for name in sorted(meters):
            m = meters[name]
            fam = f"{base}_{_prom_name(name)}"
            _family(f"{fam}_total", "counter", catalog.get(name, ""))
            lines.append(f"{fam}_total{label} {m.count}")
            _family(f"{fam}_rate1m", "gauge")
            lines.append(f"{fam}_rate1m{label} {m.rate_1m:.6g}")
        for name in sorted(timers):
            t = timers[name]
            fam = f"{base}_{_prom_name(name)}_ms"
            _family(fam, "summary", catalog.get(name, ""))
            p50, p95, p99 = t.percentiles((50, 95, 99))
            for q, v in (("0.5", p50), ("0.95", p95), ("0.99", p99)):
                lines.append(
                    f'{fam}{{scope="{_prom_label(reg.scope)}",quantile="{q}"}} {v:.6g}'
                )
            lines.append(f"{fam}_sum{label} {t.total_ms:.6g}")
            lines.append(f"{fam}_count{label} {t.count}")
        for name in sorted(gauges):
            v = _prom_value(gauges[name].value)
            if v is None:
                continue
            fam = f"{base}_{_prom_name(name)}"
            _family(fam, "gauge", catalog.get(name, ""))
            lines.append(f"{fam}{label} {v}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Per-role metric-name catalogs — the single source of truth.
#
# Every ``meter("...")`` / ``timer("...")`` / ``gauge("...")`` name used
# in the codebase must appear here (``tools/metrics_lint.py`` enforces
# it as a tier-1 test).  Dynamic name parts are declared with ``*``
# (e.g. ``phase.*`` covers ``phase.staging``); entries are
# name -> one-line description (rendered as Prometheus HELP).
# ---------------------------------------------------------------------------

BROKER_METRIC_CATALOG: Dict[str, str] = {
    "queries": "queries received (post-parse routing attempts included)",
    "queriesDropped": "queries rejected by the admission front door "
    "(any tier: quota / concurrency / overload)",
    # adaptive admission plane (broker/admission.py)
    "admission.shedQuota": "queries shed by the per-table QPS token bucket",
    "admission.shedConcurrency": "queries shed by the per-table in-flight cap",
    "admission.shedOverload": "queries shed pre-scatter because every "
    "covering server's AIMD window was exhausted",
    "admission.windowDecreases": "AIMD multiplicative window decreases "
    "(saturation evidence observed)",
    "admission.inflight": "queries currently inside the broker, all tables",
    "slowQueries": "queries recorded into the slow-query log",
    "failoverRetries": "scatter batches re-issued to an alternate replica",
    "hedgesSent": "speculative duplicate attempts sent to a second replica",
    "queryTotal": "end-to-end broker latency per query",
    "phase.parse": "PQL parse + optimize time",
    "phase.route": "routing-table lookup + batch build time",
    "scatterGather": "scatter-gather wall time per query",
    "reduce": "partial-merge + finalize time per query",
    "serverLatency": "per-attempt server round-trip latency",
    # cost-accounting plane (merged per-query cost vector totals)
    "cost.docsScanned": "documents scanned, summed over merged responses",
    "cost.bytesScanned": "column bytes touched, summed over merged responses",
    "cost.deviceMs": "per-query device-kernel ms (merged cost vector)",
    "cost.hostMs": "per-query host-path ms (merged cost vector)",
    "table.*.docsScanned": "per-table documents scanned (cost attribution)",
    "table.*.bytesScanned": "per-table column bytes touched (cost attribution)",
    # workload-introspection plane (utils/planstats.py, /debug/workload)
    "workload.recorded": "responses folded into the per-plan-digest "
    "workload registry",
    "workload.digests": "distinct plan-shape digests currently tracked",
    "explain.queries": "EXPLAIN / EXPLAIN ANALYZE queries handled",
    # distributed-join plane (broker/joinplan.py planner + coordinator)
    "join.queries": "join queries planned by this broker",
    "join.failed": "join queries that completed with exceptions",
    "join.strategy.colocated": "joins executed with the colocated "
    "partitioned strategy (zero exchange bytes)",
    "join.strategy.broadcast": "joins executed by broadcasting the "
    "build side to every probe server",
    "join.strategy.shuffle": "joins executed through the key-hash "
    "shuffle exchange",
    "join.heavyHitterSplits": "heavy-hitter keys split-and-replicated "
    "across shuffle owners instead of hot-spotting one server",
    "join.shuffleBytes": "exchange bytes shipped to shuffle owners",
    "join.broadcastBytes": "build-side bytes shipped across all "
    "broadcast probe servers",
    "join.planMs": "join planning + coordination wall ms per query",
    # partition-tolerance plane (ISSUE 9): a partitioned broker keeps
    # serving from its last versioned snapshot and says so
    # SLO & tail-latency attribution plane (ISSUE 11)
    "history.ticks": "metric-history samples recorded into the ring "
    "(utils/timeseries.py, served at /debug/history)",
    "history.series": "distinct series in the latest history sample",
    "slo.burning": "tables currently burning their error budget on BOTH "
    "the fast and slow windows",
    "slo.worstBurnRate5m": "worst per-table burn rate over the fast "
    "(default 5m) window",
    "slo.worstBurnRate1h": "worst per-table burn rate over the slow "
    "(default 1h) window",
    "tails.observed": "completed queries offered to the tail sampler",
    "tails.retained": "tail traces kept (slow / failed / partial / "
    "1-in-N sampled)",
    "tails.ring": "retained tail traces currently held in the ring",
    "flightrec.dumps": "flight-recorder bundles written on notable events",
    "flightrec.bundles": "flight-recorder bundles currently on disk",
    "controller.unreachable": "1 while cluster-state polls are failing "
    "(serving from the last versioned snapshot)",
    "controller.pollFailures": "failed cluster-state polls (partition / "
    "controller outage; full-jitter retried)",
    "controller.allDeadSnapshotsHeld": "cluster-state snapshots listing "
    "NO live servers ignored in favor of the last routing (the "
    "controller may be the partitioned one)",
    "netfaults.*": "injected link faults observed by this role's "
    "transports (dropped/replyDropped/delayed/duplicated/flaky)",
    # correctness & freshness audit plane (ISSUE 19): replica
    # double-scatter sampling + event-time freshness on responses
    "audit.replicaChecks": "sampled queries double-scattered to an "
    "alternate covering replica and compared (accounting stripped)",
    "audit.replicaDivergences": "replica pairs whose stripped payloads "
    "differed — a real correctness signal, flight-recorded",
    "audit.replicaDropped": "replica-audit samples dropped (queue full "
    "or sampler budget exhausted — never blocks serving)",
    "audit.replicaErrors": "replica-audit probes that errored before a "
    "comparison (either side failed; not counted as divergence)",
    "freshness.lagMs": "event-time staleness of merged responses "
    "(now - min realtime watermark across merged parts)",
    "freshness.*.lagMs": "per-table freshnessMs of the latest "
    "realtime-serving response",
}

SERVER_METRIC_CATALOG: Dict[str, str] = {
    "queries": "instance requests handled",
    "queriesShed": "requests shed by the saturated scheduler (210)",
    "queriesAbandoned": "requests whose deadline expired while queued",
    "segmentsMissedServing": "requested segments this server could not serve",
    "crcFailures": "segment integrity (CRC) verification failures",
    "quarantinedSegments": "corrupt segment copies pulled out of serving",
    "queryExecution": "end-to-end server handle_request latency",
    "scheduler.pending": "queries queued-or-running on the scheduler",
    "phase.schedulerWait": "time from submit to worker dequeue",
    # fair-share scheduling plane (per-table DRR queues)
    "fairshare.activeTables": "tables with a non-empty scheduler queue",
    "fairshare.shed": "submits shed by the global or per-table "
    "fair-share pending cap (210 on the wire)",
    "phase.*": "per-stage executor phase timers (staging, planBuild, "
    "laneWait, planExec, finalize, indexPath, hostPath, hostFailover, "
    "laneDispatch)",
    "heal.deviceFailures": "device launch failures (classified)",
    "heal.deviceRetries": "transient device failures retried on device",
    "heal.hostFailovers": "queries transparently served via the host path",
    "heal.poisonSkips": "queries that skipped a quarantined device plan",
    "heal.resourceExhausted": "device allocation failures healed by "
    "residency demotion + retry (never poisoned)",
    "heal.auditQuarantines": "(plan digest, tier) pairs quarantined by "
    "the shadow differential auditor (wrong answer caught)",
    "heal.auditTierSkips": "queries steered off an audit-quarantined "
    "serving tier (answered by the next tier / host)",
    "lane.depth": "device-lane queue depth (lane-group servers: summed "
    "over every lane)",
    "lane.inflight": "device-lane launches currently inside the launch call",
    "lane.open": "completed dispatches still coalescible (program running)",
    "lane.dispatches": "kernel launches issued by the device lane(s)",
    "lane.coalesced": "queries coalesced onto an identical in-flight dispatch",
    "lane.shed": "lane waiters shed at dequeue (deadline expired)",
    "lane.deviceFailures": "launch failures surfaced by the lane",
    "lane.restarts": "lane threads restarted by the stall watchdog",
    # mesh execution plane (engine/mesh.py + dispatch.LaneGroup): lane
    # groups expose per-lane twins of every lane series at lane.<i>.*,
    # and the topology itself is gauged
    "lane.*.depth": "per-chip-group lane queue depth (lane.<i>.depth)",
    "lane.*.open": "per-lane completed dispatches still coalescible",
    "lane.*.inflight": "per-lane launches inside the launch call",
    "lane.*.*": "per-lane twins of the lane.* meters "
    "(lane.<i>.dispatches/coalesced/shed/deviceFailures/restarts)",
    "mesh.lanes": "chip-group lanes this server serves with",
    "mesh.devices": "devices across every chip group",
    "mesh.devicesPerLane": "chips per lane group (mesh shape)",
    # cross-query micro-batching tier (engine/dispatch.py BatchSpec):
    # same-plan distinct-literal dispatches stacked into one vmapped
    # launch; occupancy = batch.queries / batch.launches
    "batch.launches": "batched kernel launches (>= 2 members stacked)",
    "batch.queries": "queries carried by batched launches (members)",
    "batch.windowClosedFull": "batch windows closed by reaching the "
    "member cap (PINOT_TPU_BATCH_MAX / the per-plan row-budget cap)",
    "batch.windowClosedTimeout": "batch windows closed by the bounded "
    "formation window expiring (PINOT_TPU_BATCH_WINDOW_MS)",
    "batch.windowClosedIdle": "batches launched without a window wait "
    "(peers already queued; the lane never idles waiting for demand)",
    # ingest-aware result cache (engine/rescache.py; opt-in via
    # PINOT_TPU_RESULT_CACHE=1)
    "rescache.hits": "queries answered from the result cache (zero "
    "device/host work, freshness fenced by staging tokens)",
    "rescache.misses": "cacheable queries that executed (and stored)",
    "rescache.puts": "results stored into the cache",
    "rescache.invalidations": "invalidation events (LLC offset "
    "advancement or segment set change)",
    "rescache.staleEvictions": "cached entries dropped because the "
    "data that produced them was superseded (staleness fence)",
    "rescache.entries": "result-cache entries currently resident",
    "rescache.bytes": "bytes pinned by resident result-cache entries",
    "rescache.enabled": "1 while the result cache is enabled "
    "(PINOT_TPU_RESULT_CACHE)",
    # cost-accounting plane: per-query cost totals on this server
    "cost.docsScanned": "documents scanned by queries on this server",
    "cost.bytesScanned": "column bytes touched by queries on this server",
    "cost.deviceMs": "per-query device-kernel ms (cost vector)",
    "cost.hostMs": "per-query host-path ms (cost vector)",
    "cost.tier.*": "per-serving-tier segment counts from the cost vector "
    "(segmentsPruned/Postings/Bitsliced/Zonemap/FullScan/Host/StarTree) — "
    "the series /debug/plans tier mixes reconcile against",
    # bit-sliced bulk-bitwise filter tier (engine/bitsliced.py, r17)
    "filter.bitsliced.queries": "queries answered by the bit-sliced "
    "bulk-bitwise tier (O(bit-width) plane passes, no row materialization)",
    "filter.bitsliced.planes": "packed bit-planes evaluated by bit-sliced "
    "kernels (filter + fused-aggregate planes)",
    "filter.bitsliced.fusedAggs": "aggregates answered by popcount-fused "
    "plane sums inside the bit-sliced kernel (no index materialization)",
    "filter.bitsliced.bytes": "packed bit-plane bytes streamed by "
    "bit-sliced kernel launches",
    # workload-introspection plane (utils/planstats.py, /debug/plans)
    "plan.recorded": "instance requests folded into the per-plan-digest "
    "stats registry",
    "plan.explains": "EXPLAIN plan requests answered without execution",
    "plan.digests": "distinct plan-shape digests currently tracked",
    # compile timeline (engine/dispatch.py lane registry): first-call
    # launch of a device-plan digest pays trace + XLA compile
    "compile.cold": "device-plan digests launched for the first time "
    "(cold compile measured; persistent-cache hits and prewarmed shapes "
    "excluded — serving-path genuine colds only)",
    "compile.warm": "device launches that reused an already-compiled plan",
    "compile.firstCallMs": "first-call (compile-inclusive) launch wall ms "
    "per device-plan digest",
    # warm-start resilience (engine/compilecache.py + server/prewarm.py):
    # the persistent compile cache splits the first-launch timeline into
    # cold / persistent / prewarmed, and the prewarm worker drives
    # compiles off the serving path
    "compile.persistentHit": "first launches of a plan digest whose XLA "
    "binary the persistent compile cache already held (restart warmth)",
    "compile.persistentMiss": "genuine cold compiles while the persistent "
    "cache was enabled (the entry is written for the next restart)",
    "compile.prewarmed": "plan digests compiled by the background prewarm "
    "worker before any serving query needed them",
    "prewarm.shapes": "workload plan shapes considered by prewarm passes",
    "prewarm.compiled": "prewarm shapes actually compiled into a lane's "
    "registry (digest-exact, off the serving path)",
    "prewarm.skipped": "prewarm shapes skipped (already compiled, "
    "off-device plan, no exemplar, or deadline-capped)",
    "prewarm.failed": "prewarm shapes that errored (parse/build/compile); "
    "the shape compiles lazily — and honestly cold — on the serving path",
    "server.warming": "1 while the prewarm worker is rebuilding the "
    "compile working set (the heartbeat-reported readiness flag)",
    "compile.costAnalyses": "device-plan digests whose static XLA cost "
    "analysis (flops / bytes accessed) landed in the compile registry",
    "compile.costAnalysisUnavailable": "device-plan digests whose backend "
    "reported no usable static cost analysis (explicit 'unavailable')",
    # device utilization & profiling plane (ISSUE 10): windowed lane
    # occupancy, cumulative transfer totals, and achieved-vs-peak
    # roofline rates against utils/platform.py declared peaks
    "device.util.busyFraction": "fraction of the recent window the device "
    "lane spent inside kernel launch calls (0 when idle)",
    "device.util.avgQueueDepth": "time-weighted average device-lane queue "
    "depth over the recent window",
    "device.util.h2dBytes": "cumulative host->device transfer bytes "
    "(segment staging + batched query-input uploads)",
    "device.util.d2hBytes": "cumulative device->host transfer bytes "
    "(packed result fetches + raw-path output reads)",
    "device.util.achievedBytesPerSec": "achieved device scan bytes/s over "
    "the recent roofline window (deviceBytes / measured deviceMs)",
    "device.util.achievedFlopsPerSec": "achieved FLOP/s over the recent "
    "roofline window (static flops per exec x execs / measured deviceMs)",
    "device.util.rooflineFraction": "best-utilized-resource achieved/peak "
    "fraction (null when no platform peak is declared)",
    # on-demand deep profiling (server/profiler.py jax.profiler bracket)
    "profile.starts": "profile capture start requests (ref-counted joins "
    "included)",
    "profile.stops": "profile capture stop requests released",
    "profile.autoStops": "captures force-stopped by the auto-stop deadline "
    "(client died mid-capture)",
    "profile.failedStarts": "capture starts that failed inside the "
    "profiler trace backend",
    "profile.active": "1 while a jax.profiler trace capture is active",
    # HBM staging ledger (engine/device.py LEDGER; per-process)
    "hbm.stagedBytes": "bytes of segment arrays currently staged in HBM",
    "hbm.highWatermarkBytes": "high-watermark of staged HBM bytes",
    "hbm.stagedTables": "staged-table cache entries currently resident",
    "hbm.evictedBytes": "staged bytes released by cache evictions",
    "hbm.qinputCacheBytes": "bytes pinned by the device query-input cache",
    # tiered residency (engine/residency.py RESIDENCY; per-process):
    # hot = HBM, warm = host-RAM packed snapshots, cold = on-disk
    "residency.hotBytes": "staged bytes resident in the hot (HBM) tier",
    "residency.warmBytes": "packed snapshot bytes in the warm (host) tier",
    "residency.coldBytes": "packed snapshot bytes spooled to the cold "
    "(disk) tier",
    "residency.hotTables": "staged-table entries in the hot tier",
    "residency.warmTables": "staged-table entries in the warm tier",
    "residency.coldTables": "staged-table entries in the cold tier",
    "residency.pressure": "hot bytes / configured HBM cap (0 = uncapped)",
    "residency.demotions": "hot->warm demotions (HBM freed, layout kept)",
    "residency.promotions": "warm/cold->hot promotions (zero re-encode)",
    "residency.coldDemotions": "warm->cold disk spills",
    "residency.coldLoads": "cold->warm disk reads (promotion or prefetch)",
    "residency.pressureDemotions": "demotions forced by a "
    "RESOURCE_EXHAUSTED heal rather than a configured cap",
    "residency.prefetches": "async cold->warm lifts ahead of dispatch",
    # distributed-join plane (engine/join.py): per-phase server counters
    "join.extracts": "join side-extraction phase requests served",
    "join.execs": "join executions (hash build + probe) served",
    "join.buildRows": "build-side rows inserted into join hash tables",
    "join.probeRows": "probe-side rows probed against join hash tables",
    "join.shuffleBytes": "shuffle-exchange bytes RECEIVED by this server "
    "(the skew-balance observable: compare across servers)",
    "join.broadcastBytes": "broadcast build-side bytes received",
    # correctness & freshness audit plane (ISSUE 19): shadow
    # differential sampling against the host oracle + event-time
    # watermarks per consuming partition
    "audit.samples": "completed queries re-executed against the host "
    "oracle by the shadow auditor (1-in-N sampled, off the serving path)",
    "audit.divergences": "shadow re-executions whose stripped payload "
    "differed from the served answer (wrong answer detected)",
    "audit.quarantines": "(plan digest, tier) quarantines placed by the "
    "shadow auditor on divergence",
    "audit.dropped": "audit samples dropped (queue full or sampler "
    "budget exhausted — auditing never blocks serving)",
    "audit.errors": "shadow re-executions that errored before a "
    "comparison (not counted as divergence)",
    "audit.queueDepth": "shadow-audit jobs currently queued",
    "audit.shadowMs": "host-oracle re-execution wall ms per audit sample",
    "audit.detectMs": "query-completion to divergence-detection wall ms",
    "freshness.lag.*": "per-(table, partition) event-time lag ms "
    "(now - max ingested event time)",
    # ingest observability (realtime consumers hosted on this server)
    "ingest.rowsConsumed": "stream rows consumed into mutable segments",
    "ingest.commitMs": "segment commit latency (convert + persist round)",
    "ingest.lag.*": "per-(table, partition) consumer lag in rows "
    "(latest available offset - consumed offset)",
    # ingest backpressure plane (realtime/backpressure.py governor)
    "ingest.paused": "1 while the ingest governor holds consumption "
    "above a memory watermark",
    "ingest.paused.*": "per-(table, partition) consumer pause flag "
    "(1 = held by the backpressure governor)",
    "ingest.pauses": "ingest pause events (high watermark crossed)",
    "ingest.resumes": "ingest resume events (back under low watermarks)",
    # partition-parallel ingest plane (realtime/pool.py, r15)
    "ingest.pool.steps": "cooperative consumer steps driven by the "
    "ingest pool's bounded workers",
    "ingest.pool.errors": "consumer steps that raised (consumer parked "
    "with a backoff, workers unaffected)",
    "ingest.pool.workers": "worker threads in the ingest consumer pool "
    "(PINOT_TPU_INGEST_CONSUMERS)",
    "ingest.pool.consumers": "realtime consumers currently registered "
    "with the ingest pool",
    # partition-tolerance plane (ISSUE 9): serving-lease fence on write
    # authority + controller reachability while riding out a partition
    "lease.held": "1 while this server holds (or never needed) a "
    "serving lease — write authority",
    "lease.renewals": "serving-lease renewals from heartbeat replies",
    "lease.expiries": "serving-lease expiries (partitioned past the "
    "lease window; write authority self-fenced)",
    "lease.blockedCommits": "completion/commit rounds frozen because "
    "the serving lease expired",
    "lease.blockedTransitions": "CONSUMING transitions deferred "
    "(unacked) while the serving lease was expired",
    # SLO & tail-latency attribution plane (ISSUE 11)
    "history.ticks": "metric-history samples recorded into the ring "
    "(utils/timeseries.py, served at /debug/history)",
    "history.series": "distinct series in the latest history sample",
    "flightrec.dumps": "flight-recorder bundles written on notable events",
    "flightrec.bundles": "flight-recorder bundles currently on disk",
    "controller.unreachable": "1 while heartbeats to the controller "
    "are failing (riding out a partition on local state)",
    "controller.heartbeatFailures": "failed controller heartbeats "
    "(full-jitter retried)",
    "netfaults.*": "injected link faults observed by this role's "
    "transports (dropped/replyDropped/delayed/duplicated/flaky)",
}

CONTROLLER_METRIC_CATALOG: Dict[str, str] = {
    "instanceRegistrations": "instance register calls accepted",
    "heartbeats": "instance heartbeats received",
    "instancesMarkedDead": "instances declared dead on missed heartbeats",
    "transitionAcks": "segment-transition acks processed",
    "clusterStatePolls": "full cluster-state snapshots served to brokers",
    "clusterStateCacheHits": "cluster-state polls answered from the "
    "version-keyed snapshot cache (no per-poll table walk)",
    "segmentUploads": "segments stored via the upload paths",
    "segmentCommits": "realtime segments committed through the LLC FSM",
    "segmentCommitMs": "controller-side commit persistence latency",
    "gateway.flaps": "dead->alive instance cycles admitted (flap hysteresis)",
    "manager.*.failures": "periodic-manager run_once failures, by manager",
    "stabilizer.rounds": "self-stabilizer convergence rounds executed",
    "stabilizer.replicasAdded": "replicas re-replicated onto live servers",
    "stabilizer.replicasDropped": "dead/draining replicas removed from ideal "
    "state after coverage was restored",
    "stabilizer.consumingReassigned": "consuming segments retired for "
    "re-creation on a live server at the committed offset",
    "stabilizer.graceDeferrals": "dead servers whose re-replication was "
    "deferred inside the grace window",
    "stabilizer.leaseDeferrals": "dead-looking servers whose replicas "
    "were NOT moved because their serving lease had not expired "
    "(possibly alive-but-partitioned)",
    "stabilizer.underReplicatedSegments": "segments currently below target "
    "replication on live servers",
    "stabilizer.drainingInstances": "instances currently draining",
    "stabilizer.deadServers": "servers currently tracked as dead",
    # proactive skew-aware rebalance plane (r15, controller/stabilizer.py)
    "rebalance.evaluations": "skew evaluations run (healthy rounds only — "
    "healing always yields first)",
    "rebalance.skewDeferrals": "skewed evaluations deferred inside the "
    "hysteresis window (one hot minute moves nothing)",
    "rebalance.movesStarted": "make-before-break phase-1 replica adds "
    "started by the rebalance planner",
    "rebalance.movesCompleted": "surplus source replicas dropped after "
    "the external view proved coverage (phase 2)",
    "rebalance.movesAborted": "moves cancelled by dropping an ERROR "
    "destination replica instead of the source",
    "rebalance.pendingMoves": "make-before-break moves currently between "
    "phase 1 (added) and phase 2 (source dropped)",
    "rebalance.imbalanceRatio": "worst per-tenant max/mean doc-x-cost "
    "load ratio seen by the last skew evaluation",
    "rebalance.prewarmDeferrals": "replica removals deferred because the "
    "surviving cover was still prewarming its compile working set "
    "(bounded by PINOT_TPU_PREWARM_TIMEOUT_S)",
    "aliveServers": "registered server instances currently alive",
    "aliveBrokers": "registered broker instances currently alive",
    "deadInstances": "registered instances currently marked dead",
    "tables": "physical tables managed",
    # partition-tolerance plane (ISSUE 9): serving leases + the
    # cluster-wide epoch fence on the commit plane / property store
    "lease.granted": "serving leases granted on heartbeat/registration "
    "replies",
    "fence.epoch": "this controller's fencing incarnation (property "
    "store cluster/epoch)",
    "fence.staleEpochRejections": "commit-plane calls typed-rejected "
    "for carrying a stale controller epoch",
    "fence.leaseRejections": "segmentCommit uploads rejected because "
    "the committer's serving lease had expired",
    "fence.committerReElections": "LLC committers re-elected after the "
    "elected one lost its serving lease mid-protocol",
    "netfaults.*": "injected link faults observed by this role's "
    "transports (dropped/replyDropped/delayed/duplicated/flaky)",
    # SLO & tail-latency attribution plane (ISSUE 11)
    "history.ticks": "metric-history samples recorded into the ring "
    "(utils/timeseries.py, served at /debug/history)",
    "history.series": "distinct series in the latest history sample",
    "flightrec.dumps": "flight-recorder bundles written on notable events",
    "flightrec.bundles": "flight-recorder bundles currently on disk",
    # correctness audit plane (ISSUE 19): periodic cross-replica
    # checksum sweep over registered segment CRCs
    "audit.sweep.runs": "cross-replica CRC sweep rounds completed",
    "audit.sweep.segmentsChecked": "segment replica-sets compared by "
    "the latest sweeps",
    "audit.sweep.skippedInstances": "instances skipped by sweeps "
    "(unreachable or no admin URL)",
    "audit.crcMismatches": "segments whose replicas currently disagree "
    "on content CRC (cross-replica divergence)",
    # disaster-recovery plane (ISSUE 20): journaled metadata durability
    "durability.journalAppends": "property-store mutations framed into "
    "the op journal (controller/journal.py)",
    "durability.snapshots": "full-state journal snapshots cut "
    "(periodic + forced backup-prep)",
    "durability.corruptRecords": "property-store record files found "
    "truncated/garbled and quarantined aside",
    "durability.recordsHealed": "property-store records regenerated "
    "from the journal-recovered state",
    "durability.journalTornTailTruncations": "torn journal tail frames "
    "truncated during recovery (crash mid-append)",
    "durability.corruptSnapshots": "journal snapshots found unreadable "
    "and quarantined (recovery fell back to the log)",
    # disaster-recovery plane (ISSUE 20): deep-store scrub + reverse
    # replication of lost/corrupt durable copies
    "deepstore.scrub.runs": "deep-store scrub rounds completed",
    "deepstore.scrub.copiesChecked": "durable copies CRC re-verified "
    "by scrub rounds",
    "deepstore.scrub.budgetDenied": "scrub checks skipped by the "
    "shared sampler budget (serving protected)",
    "deepstore.corruptCopies": "durable copies found lost or corrupt",
    "deepstore.repairs": "durable copies re-replicated from a live "
    "server's verified replica (reverse replication)",
    "deepstore.repairFailures": "corrupt durable copies with no "
    "healthy donor replica available",
    "deepstore.suspectsReported": "store-copy suspects reported by "
    "server fetch paths (CRC-failing downloads)",
    "deepstore.suspectsPending": "store-copy suspects queued for the "
    "next scrub round",
    "*.missingReplicas": "per-table replicas missing from the external view",
    "*.errorReplicas": "per-table replicas in ERROR state",
    "*.percentSegmentsAvailable": "per-table % of segments with a live replica",
    "*.segmentCount": "per-table segment count",
}

METRIC_CATALOGS: Dict[str, Dict[str, str]] = {
    "broker": BROKER_METRIC_CATALOG,
    "server": SERVER_METRIC_CATALOG,
    "controller": CONTROLLER_METRIC_CATALOG,
}
