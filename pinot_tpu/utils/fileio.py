"""Durable file-write helpers shared by the stream broker's log
recovery/offset persistence and the controller property store.

The reference gets this durability from ZooKeeper (writes are
replicated + fsynced by ZK, ``common/metadata/`` records); the
file-backed analogs here need tmp+fsync+rename so a crash at any point
leaves either the old or the new content, never neither."""
from __future__ import annotations

import os
import tempfile


def atomic_write(path: str, text, binary: bool = False, fsync: bool = True) -> None:
    """Write ``text`` to ``path`` via a same-directory temp file with
    fsync-before-rename (crash-durable whole-file replace).

    ``fsync=False`` skips both the file fsync and the directory fsync:
    the replace is still atomic against concurrent readers (they see
    old or new content, never a partial file) but may revert to the old
    content after power loss.  Callers that journal their mutations
    (the property store) use this for the per-key mirror files, since
    the journal — not the mirror — is the recovery source of truth.
    """
    dirname = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            f.write(text)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory too: without it the rename itself may not
        # survive power loss, reverting to the old file
        if fsync:
            fsync_dir(dirname)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def fsync_dir(dirname: str) -> None:
    """fsync a directory so renames/creates within it are durable."""
    dfd = os.open(dirname or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
