"""Durable file-write helpers shared by the stream broker's log
recovery/offset persistence and the controller property store.

The reference gets this durability from ZooKeeper (writes are
replicated + fsynced by ZK, ``common/metadata/`` records); the
file-backed analogs here need tmp+fsync+rename so a crash at any point
leaves either the old or the new content, never neither."""
from __future__ import annotations

import os
import tempfile


def atomic_write(path: str, text, binary: bool = False) -> None:
    """Write ``text`` to ``path`` via a same-directory temp file with
    fsync-before-rename (crash-durable whole-file replace)."""
    dirname = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory too: without it the rename itself may not
        # survive power loss, reverting to the old file
        dfd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
