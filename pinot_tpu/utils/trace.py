"""Per-request tracing.

The reference registers a requestId-scoped trace registry and wraps
worker threads so operators can log step latencies
(``core/util/trace/TraceContext.java:41``, ``TraceRunnable``); the trace
rides back in DataTable metadata and is merged per server
(``BrokerReduceService.java:84-87``).  Here a TraceContext collects
(span -> ms) under a scope name and attaches to the result's trace dict;
thread inheritance uses contextvars instead of thread wrappers.
"""
from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_current: contextvars.ContextVar[Optional["TraceContext"]] = contextvars.ContextVar(
    "pinot_tpu_trace", default=None
)


class TraceContext:
    def __init__(self, enabled: bool = False, scope: str = "") -> None:
        self.enabled = enabled
        self.scope = scope
        self.spans: List[Tuple[str, float]] = []

    @contextmanager
    def span(self, name: str):
        if not self.enabled:
            yield
            return
        token = _current.set(self)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.spans.append((name, (time.perf_counter() - t0) * 1000.0))
            _current.reset(token)

    def add(self, name: str, ms: float) -> None:
        if self.enabled:
            self.spans.append((name, ms))

    def to_dict(self) -> Dict[str, Any]:
        if not self.enabled:
            return {}
        return {self.scope: [{"span": n, "ms": round(ms, 3)} for n, ms in self.spans]}


def current_trace() -> Optional[TraceContext]:
    return _current.get()
