"""Per-request distributed tracing: hierarchical span trees.

The reference registers a requestId-scoped trace registry and wraps
worker threads so operators can log step latencies
(``core/util/trace/TraceContext.java:41``, ``TraceRunnable``); the trace
rides back in DataTable metadata and is merged per server
(``BrokerReduceService.java:84-87``).

Here each role builds a span TREE per request: every span carries a
scope-prefixed id, a parent id, a wall-clock anchor (epoch ms, so
broker and server trees align on one waterfall), a duration, and a
tag dict.  Spans serialize as plain dicts so they ride the DataTable
``trace`` metadata unchanged and merge broker-side into
``BrokerResponse.traceInfo`` (the broker re-parents each server tree
under the scatter attempt that carried it — ``broker/broker.py``).

Span dict schema (the wire/JSON contract, see README "Observability"):

    {"span": name, "id": "scope:n", "parent": "scope:m" | None,
     "startMs": epoch_ms, "ms": duration_ms, "tags": {..}}

``tags`` is omitted when empty; events are spans with ``ms == 0``.

ZERO-OVERHEAD WHEN DISABLED: a disabled context's ``span()`` returns a
shared no-op context manager and ``add``/``event`` return immediately —
no span dicts, no generator frames.  ``SPAN_ALLOCATIONS`` counts every
span dict ever built so tests can assert the disabled path allocates
none.  Parenting uses contextvars (a per-thread span stack), not thread
wrappers.
"""
from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Dict, List, Optional

_current: contextvars.ContextVar[Optional["TraceContext"]] = contextvars.ContextVar(
    "pinot_tpu_trace", default=None
)
# stack of span ids for the current thread/task: the top is the parent
# of the next span opened on this thread
_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "pinot_tpu_trace_stack", default=()
)

# module-wide count of span dicts ever allocated — the disabled-trace
# zero-overhead guard (tests assert no delta across an untraced query)
SPAN_ALLOCATIONS = 0


class _NullSpan:
    """Shared no-op context manager for disabled traces."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Open-span context manager: allocates the span dict on enter (so
    children opened inside can reference its id), fills the duration on
    exit, and keeps the contextvar parent stack balanced."""

    __slots__ = ("_ctx", "_span", "_token", "_t0")

    def __init__(self, ctx: "TraceContext", name: str, tags: Dict[str, Any]) -> None:
        self._ctx = ctx
        self._span = ctx._alloc(name, 0.0, time.time() * 1000.0, _parent_id(), tags)
        self._token = None
        self._t0 = 0.0

    def __enter__(self):
        self._token = _stack.set(_stack.get() + (self._span["id"],))
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, *exc):
        self._span["ms"] = round((time.perf_counter() - self._t0) * 1000.0, 3)
        if self._token is not None:
            _stack.reset(self._token)
        return False


def _parent_id() -> Optional[str]:
    stack = _stack.get()
    return stack[-1] if stack else None


class TraceContext:
    """One role's span tree for one request (requestId-scoped)."""

    __slots__ = ("enabled", "scope", "trace_id", "spans", "_seq", "_lock")

    def __init__(self, enabled: bool = False, scope: str = "", trace_id: str = "") -> None:
        self.enabled = enabled
        self.scope = scope
        self.trace_id = trace_id
        self.spans: List[Dict[str, Any]] = []
        self._seq = 0
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------
    def _alloc(
        self,
        name: str,
        ms: float,
        start_ms: float,
        parent: Optional[str],
        tags: Dict[str, Any],
    ) -> Dict[str, Any]:
        global SPAN_ALLOCATIONS
        with self._lock:
            self._seq += 1
            sid = f"{self.scope}:{self._seq}"
            span: Dict[str, Any] = {
                "span": name,
                "id": sid,
                "parent": parent,
                "startMs": round(start_ms, 3),
                "ms": ms,
            }
            if tags:
                span["tags"] = dict(tags)
            self.spans.append(span)
            SPAN_ALLOCATIONS += 1
            return span

    def span(self, name: str, **tags):
        """Open a timed child span (context manager).  Nesting on the
        same thread parents automatically via the contextvar stack."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, tags)

    def add(
        self,
        name: str,
        ms: float,
        start_ms: Optional[float] = None,
        parent: Optional[str] = "__auto__",
        **tags,
    ) -> Optional[str]:
        """Record an already-measured span; returns its id.  ``start_ms``
        defaults to now minus the duration; ``parent`` defaults to the
        calling thread's current span (pass ``None`` for a root)."""
        if not self.enabled:
            return None
        if start_ms is None:
            start_ms = time.time() * 1000.0 - ms
        p = _parent_id() if parent == "__auto__" else parent
        return self._alloc(name, round(ms, 3), start_ms, p, tags)["id"]

    def event(self, name: str, **tags) -> Optional[str]:
        """Zero-duration marker span (retry / failover / coalesce-hit)."""
        if not self.enabled:
            return None
        return self._alloc(name, 0.0, time.time() * 1000.0, _parent_id(), tags)["id"]

    # -- export --------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        """{scope: [span dicts]} — the shape that rides DataTable
        ``trace`` metadata; empty when disabled or nothing recorded."""
        if not self.enabled or not self.spans:
            return {}
        with self._lock:
            return {self.scope: list(self.spans)}


# a single shared disabled context: callers on the untraced path reuse
# it instead of constructing a TraceContext per request
NULL_TRACE = TraceContext(enabled=False)


def current_trace() -> Optional[TraceContext]:
    return _current.get()


def set_current(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the thread's current trace; returns the token
    for ``reset_current``.  Used by scheduler workers, which do not
    inherit the submitting thread's context."""
    return _current.set(ctx)


def reset_current(token) -> None:
    _current.reset(token)


def merge_scope(
    scopes: Dict[str, List[Dict[str, Any]]],
    incoming: Dict[str, List[Dict[str, Any]]],
    root_parent: Optional[str] = None,
) -> None:
    """Merge one reply's {scope: spans} into an accumulating scope map.

    Root spans (parent None) of each incoming tree are re-parented onto
    ``root_parent`` (the broker's serverAttempt span), linking all trees
    into one.  When the same scope already exists (two batches answered
    by one server), the incoming tree is stored under ``scope#k`` with
    its internal ids rewritten, so parent links stay unambiguous."""
    for scope, spans in incoming.items():
        key = scope
        k = 1
        while key in scopes:
            k += 1
            key = f"{scope}#{k}"
        if key != scope:
            prefix = f"{scope}:"
            new_prefix = f"{key}:"

            def _remap(sid):
                if isinstance(sid, str) and sid.startswith(prefix):
                    return new_prefix + sid[len(prefix):]
                return sid

            spans = [
                dict(s, id=_remap(s.get("id")), parent=_remap(s.get("parent")))
                for s in spans
            ]
        else:
            spans = [dict(s) for s in spans]
        if root_parent is not None:
            for s in spans:
                if s.get("parent") is None:
                    s["parent"] = root_parent
        scopes[key] = spans
