"""Time-series flight recorder: a bounded ring of metric snapshots.

Every observability surface before this PR is point-in-time: a scrape
sees the current EWMA/percentiles and nothing else, so "when did p99
start burning, and what did the cluster look like at that moment" was
unanswerable after the fact.  ``HistoryRecorder`` closes that gap: one
daemon thread per role snapshots the role's metric registries on a
cadence (default 5s, ``PINOT_TPU_HISTORY_INTERVAL_S``) into a bounded
ring (default 720 samples = 1h at 5s, ``PINOT_TPU_HISTORY_N``), served
at ``GET /debug/history?series=&windowS=`` on every role's admin
surface.

Each sample is a flat ``{series: value}`` dict:

- meters   -> ``<name>.count`` (cumulative) and ``<name>.rate1m``
- timers   -> ``<name>.count``, ``<name>.p50Ms``, ``<name>.p99Ms``
- gauges   -> ``<name>`` (numeric values only)
- extra providers (``register_provider``) merge additional series into
  the same sample — the broker's per-table SLO counters ride here.

Cumulative series + the ring give windowed deltas for free
(``window_delta``), which is exactly what multi-window SLO burn rates
(utils/slo.py) and the flight-recorder triggers (utils/flightrec.py)
consume; both run as ``add_tick_hook`` callbacks on the recorder's own
cadence, so the whole history plane costs ONE thread per role.

Ticks are also callable explicitly (``tick(now=...)``) with an
injectable clock, so chaos scenarios and unit tests drive the timeline
deterministically instead of sleeping out wall-clock windows.

Thread hygiene: every recorder registers in a module list; a STOPPED
recorder whose thread survives ``stop()`` is a leak and the conftest
guard (``leaked_recorder_threads``) fails the test that caused it —
the same contract as lane/scheduler/manager threads.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger(__name__)

# (recorder, thread) for every recorder that ever started a thread —
# consulted by the conftest thread-leak guard.  Bounded in practice by
# process lifetime; entries of exited threads are pruned on scan.
_RECORDERS: List[Tuple["HistoryRecorder", threading.Thread]] = []
_RECORDERS_LOCK = threading.Lock()


def leaked_recorder_threads(grace_s: float = 2.0) -> List[threading.Thread]:
    """Threads of STOPPED recorders still alive after ``grace_s`` —
    recorders still running (module fixtures, live roles) are exempt."""
    deadline = time.monotonic() + grace_s
    leaked: List[threading.Thread] = []
    with _RECORDERS_LOCK:
        entries = list(_RECORDERS)
    for rec, thread in entries:
        if not rec.stopped or not thread.is_alive():
            continue
        thread.join(timeout=max(0.0, deadline - time.monotonic()))
        if thread.is_alive():
            leaked.append(thread)
    with _RECORDERS_LOCK:
        _RECORDERS[:] = [(r, t) for r, t in _RECORDERS if t.is_alive()]
    return leaked


def _flatten_registry(reg) -> Dict[str, float]:
    """One registry -> flat numeric series (see module docstring)."""
    out: Dict[str, float] = {}
    with reg._lock:
        meters = dict(reg._meters)
        timers = dict(reg._timers)
        gauges = dict(reg._gauges)
    for name, m in meters.items():
        out[f"{name}.count"] = m.count
        out[f"{name}.rate1m"] = round(m.rate_1m, 4)
    for name, t in timers.items():
        p50, p99 = t.percentiles((50, 99))
        out[f"{name}.count"] = t.count
        out[f"{name}.p50Ms"] = round(p50, 3)
        out[f"{name}.p99Ms"] = round(p99, 3)
    for name, g in gauges.items():
        v = g.value
        if isinstance(v, bool):
            out[name] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[name] = v
    return out


class HistoryRecorder:
    """Bounded ring of flat metric samples, fed by one daemon thread
    (or explicit ``tick()`` calls — both are safe concurrently)."""

    def __init__(
        self,
        registries,
        interval_s: Optional[float] = None,
        capacity: Optional[int] = None,
        metrics=None,
        clock: Callable[[], float] = time.time,
        start: bool = True,
    ) -> None:
        if not isinstance(registries, (list, tuple)):
            registries = [registries]
        self.registries = list(registries)
        if interval_s is None:
            interval_s = float(os.environ.get("PINOT_TPU_HISTORY_INTERVAL_S", "5"))
        if capacity is None:
            capacity = int(os.environ.get("PINOT_TPU_HISTORY_N", "720"))
        self.interval_s = max(0.05, interval_s)
        self.capacity = max(2, capacity)
        self._ring: Deque[Tuple[float, Dict[str, float]]] = deque(
            maxlen=self.capacity
        )
        self._providers: List[Callable[[], Dict[str, float]]] = []
        self._hooks: List[Callable[[float], None]] = []
        self._lock = threading.Lock()
        self._clock = clock
        self._metrics = metrics
        if metrics is not None:
            # metric hygiene: the history.* series exist from construction
            metrics.meter("history.ticks")
            metrics.gauge("history.series").set_fn(self.series_count)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------
    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="history-recorder", daemon=True
        )
        self._thread.start()
        with _RECORDERS_LOCK:
            _RECORDERS.append((self, self._thread))

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a sick gauge must not kill the recorder
                logger.warning("history tick failed", exc_info=True)

    # -- write side ----------------------------------------------------
    def register_provider(self, fn: Callable[[], Dict[str, float]]) -> None:
        """Merge ``fn()``'s flat series into every sample (e.g. the
        broker's per-table SLO counters)."""
        self._providers.append(fn)

    def add_tick_hook(self, fn: Callable[[float], None]) -> None:
        """Run ``fn(sample_ts)`` after every sample lands (outside the
        ring lock) — SLO evaluation and flight-recorder triggers ride
        the recorder's cadence instead of owning threads."""
        self._hooks.append(fn)

    def tick(self, now: Optional[float] = None) -> Dict[str, float]:
        """Take one sample now; returns the sample dict."""
        if now is None:
            now = self._clock()
        sample: Dict[str, float] = {}
        for reg in self.registries:
            sample.update(_flatten_registry(reg))
        for fn in self._providers:
            try:
                sample.update(fn())
            except Exception:
                logger.warning("history provider failed", exc_info=True)
        with self._lock:
            self._ring.append((now, sample))
        if self._metrics is not None:
            self._metrics.meter("history.ticks").mark()
        for fn in self._hooks:
            try:
                fn(now)
            except Exception:
                logger.warning("history tick hook failed", exc_info=True)
        return sample

    # -- read side -----------------------------------------------------
    def series_count(self) -> int:
        with self._lock:
            return len(self._ring[-1][1]) if self._ring else 0

    def sample_count(self) -> int:
        with self._lock:
            return len(self._ring)

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            if not self._ring:
                return None
            return self._ring[-1][1].get(name)

    def window_delta(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[Tuple[float, float]]:
        """``(value_now - value_then, actual_window_s)`` for a CUMULATIVE
        series over the trailing window — ``then`` is the newest sample
        at least ``window_s`` old (the oldest held sample when the ring
        is younger than the window, so short-lived processes still
        report a meaningful partial-window figure).  None when the
        series needs two samples it doesn't have."""
        if now is None:
            now = self._clock()
        horizon = now - window_s
        with self._lock:
            samples = [(ts, s.get(name)) for ts, s in self._ring]
        points = [(ts, v) for ts, v in samples if v is not None]
        if len(points) < 2:
            return None
        newest_ts, newest_v = points[-1]
        base_ts, base_v = points[0]
        for ts, v in points:
            if ts <= horizon:
                base_ts, base_v = ts, v
            else:
                break
        if newest_ts <= base_ts:
            return None
        return newest_v - base_v, newest_ts - base_ts

    def query_from_qs(self, query_string: str) -> Dict[str, Any]:
        """``GET /debug/history`` adapter shared by every role's HTTP
        handler: parses ``series=`` (comma-separated name prefixes) and
        ``windowS=`` (trailing window seconds; invalid values degrade to
        the full ring) out of the raw URL query string."""
        from urllib.parse import parse_qs

        qs = parse_qs(query_string or "")
        series = [s for s in (qs.get("series") or [""])[0].split(",") if s]
        window = (qs.get("windowS") or [None])[0]
        try:
            window_s = float(window) if window else None
        except ValueError:
            window_s = None
        return self.query(series=series or None, window_s=window_s)

    def query(
        self,
        series: Optional[Iterable[str]] = None,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """``/debug/history`` payload: columnar ``{name: [[ts, v],..]}``.
        ``series`` filters by exact name OR prefix (comma form on the
        endpoint); ``window_s`` keeps only the trailing window."""
        if now is None:
            now = self._clock()
        prefixes = [p for p in (series or ()) if p]
        horizon = None if window_s is None else now - float(window_s)
        with self._lock:
            samples = list(self._ring)
        out: Dict[str, List[List[float]]] = {}
        for ts, sample in samples:
            if horizon is not None and ts < horizon:
                continue
            for name, v in sample.items():
                if prefixes and not any(name.startswith(p) for p in prefixes):
                    continue
                out.setdefault(name, []).append([round(ts, 3), v])
        return {
            "intervalS": self.interval_s,
            "capacity": self.capacity,
            "samples": len(samples),
            **({"windowS": float(window_s)} if window_s is not None else {}),
            "series": out,
        }
