"""Pure-Python LZ4 decompression (frame + block formats).

Kafka producers using codec 3 compress MessageSets with LZ4.  Kafka's
own wrapping is the standard LZ4 *frame* format (magic ``0x184D2204``)
— with the historical quirk that pre-0.10 clients computed the frame
header checksum over the wrong bytes (KAFKA-3160).  No lz4 library
ships in this image, so both formats are implemented directly from the
public spec (https://github.com/lz4/lz4/blob/dev/doc):

- ``decompress_block``: the raw block format — a sequence of
  (literals, back-reference) pairs.  Overlapping matches (offset <
  length) replicate bytes, e.g. offset 1 is RLE.
- ``decompress_frame``: frame descriptor + data blocks.  Checksums
  are parsed and *skipped*, not verified — this makes the reader
  compatible with both the correct and the KAFKA-3160-broken header
  checksum; CRC integrity for Kafka messages is already enforced
  per-message by ``decode_message_set``.
- ``compress_block`` / ``compress_frame``: a correct greedy
  hash-table compressor emitting spec-valid frames (real xxHash32
  header checksum, so conformant external readers accept the output).
  It exists for round-trip testing and for the protocol-compat shim's
  producers; ratio is not the point.

Reference behavior target: Kafka's lz4 MessageSet codec as consumed by
``core/realtime/impl/kafka/SimpleConsumerWrapper.java`` (which defers
to kafka-clients' ``KafkaLZ4BlockInputStream``).
"""
from __future__ import annotations

import struct

FRAME_MAGIC = 0x184D2204
_SKIP_MAGIC_MIN = 0x184D2A50
_SKIP_MAGIC_MAX = 0x184D2A5F

_MIN_MATCH = 4


def _decode_block_into(
    out: bytearray, data: bytes, window_start: int, max_len: int | None
) -> None:
    """Decode one raw LZ4 block, appending to ``out``.  Matches may
    reach back to ``out[window_start:]`` (the frame's linked-block
    window — ``window_start == len(out)`` means an independent block);
    ``max_len`` bounds the total ``out`` length BEFORE any copy runs,
    so attacker-shaped length fields can't balloon memory."""
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        # literals ------------------------------------------------------
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise ValueError("lz4: truncated literal length")
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise ValueError("lz4: literal run past end of block")
        if max_len is not None and len(out) + lit_len > max_len:
            raise ValueError("lz4: output exceeds declared size")
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos == n:
            break  # last sequence carries only literals
        # match ---------------------------------------------------------
        if pos + 2 > n:
            raise ValueError("lz4: truncated match offset")
        offset = data[pos] | (data[pos + 1] << 8)
        pos += 2
        if offset == 0:
            raise ValueError("lz4: zero match offset")
        if offset > len(out) - window_start:
            raise ValueError("lz4: match offset outside window")
        match_len = (token & 0x0F) + _MIN_MATCH
        if (token & 0x0F) == 15:
            while True:
                if pos >= n:
                    raise ValueError("lz4: truncated match length")
                b = data[pos]
                pos += 1
                match_len += b
                if b != 255:
                    break
        if max_len is not None and len(out) + match_len > max_len:
            raise ValueError("lz4: output exceeds declared size")
        start = len(out) - offset
        if offset >= match_len:
            out += out[start : start + match_len]
        else:
            # overlapping match: replicate the period by doubling
            # slices instead of per-byte appends (offset 1 == RLE)
            chunk = bytes(out[start:])
            reps = match_len // len(chunk) + 1
            out += (chunk * reps)[:match_len]


def decompress_block(data: bytes, max_output: int | None = None) -> bytes:
    """Decode one standalone raw LZ4 block."""
    out = bytearray()
    _decode_block_into(out, data, 0, max_output)
    return bytes(out)


def decompress_frame(data: bytes) -> bytes:
    """Decode a standard LZ4 frame (possibly preceded by skippable
    frames); trailing bytes after the EndMark are ignored."""
    pos = 0
    n = len(data)
    while True:
        if pos + 4 > n:
            raise ValueError("lz4: truncated frame magic")
        magic = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        if _SKIP_MAGIC_MIN <= magic <= _SKIP_MAGIC_MAX:
            if pos + 4 > n:
                raise ValueError("lz4: truncated skippable frame")
            size = struct.unpack_from("<I", data, pos)[0]
            pos += 4 + size
            continue
        if magic != FRAME_MAGIC:
            raise ValueError(f"lz4: bad frame magic 0x{magic:08x}")
        break
    if pos + 2 > n:
        raise ValueError("lz4: truncated frame descriptor")
    flg = data[pos]
    bd = data[pos + 1]
    pos += 2
    version = (flg >> 6) & 0x03
    if version != 1:
        raise ValueError(f"lz4: unsupported frame version {version}")
    block_indep = bool(flg & 0x20)
    block_checksum = bool(flg & 0x10)
    content_size_flag = bool(flg & 0x08)
    content_checksum = bool(flg & 0x04)
    if flg & 0x01:
        raise ValueError("lz4: dictionary frames not supported")
    bs_code = (bd >> 4) & 0x07
    if bs_code < 4:
        raise ValueError(f"lz4: invalid block max-size code {bs_code}")
    block_max = 1 << (8 + 2 * bs_code)  # 4:64KB 5:256KB 6:1MB 7:4MB
    content_size = None
    if content_size_flag:
        if pos + 8 > n:
            raise ValueError("lz4: truncated content size")
        content_size = struct.unpack_from("<Q", data, pos)[0]
        pos += 8
    pos += 1  # HC byte — parsed, not verified (KAFKA-3160 tolerance)

    out = bytearray()
    while True:
        if pos + 4 > n:
            raise ValueError("lz4: truncated block header")
        raw = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        if raw == 0:  # EndMark
            break
        uncompressed = bool(raw & 0x80000000)
        size = raw & 0x7FFFFFFF
        if size > block_max:
            raise ValueError("lz4: block larger than frame's declared max")
        if pos + size > n:
            raise ValueError("lz4: truncated data block")
        block = data[pos : pos + size]
        pos += size
        if block_checksum:
            pos += 4  # parsed, not verified
        if uncompressed:
            out += block
        else:
            # linked blocks (librdkafka's LZ4F default) may back-
            # reference up to 64KB into prior blocks' output
            window_start = len(out) if block_indep else max(0, len(out) - 65536)
            _decode_block_into(out, block, window_start, len(out) + block_max)
    if content_checksum:
        pos += 4  # parsed, not verified
    if content_size is not None and len(out) != content_size:
        raise ValueError(
            f"lz4: content size mismatch ({len(out)} != {content_size})"
        )
    return bytes(out)


def decompress(data: bytes) -> bytes:
    """Frame-or-block entry point: frames are self-identifying by magic;
    anything else is treated as one raw block."""
    if len(data) >= 4:
        magic = struct.unpack_from("<I", data, 0)[0]
        if magic == FRAME_MAGIC or _SKIP_MAGIC_MIN <= magic <= _SKIP_MAGIC_MAX:
            return decompress_frame(data)
    return decompress_block(data)


# -- compression (testing + shim producers) ----------------------------


def compress_block(data: bytes) -> bytes:
    """Greedy single-pass LZ4 block compressor.

    Spec-conformant output: matches are >= 4 bytes, the final sequence
    is literals-only, and (as the reference encoder guarantees) the
    last 5 bytes are always emitted as literals with no match starting
    within 12 bytes of the end.
    """
    n = len(data)
    out = bytearray()

    def emit(lit_start: int, lit_end: int, offset: int, match_len: int) -> None:
        lit_len = lit_end - lit_start
        ml = 0 if match_len == 0 else match_len - _MIN_MATCH
        token_lit = 15 if lit_len >= 15 else lit_len
        token_ml = 15 if ml >= 15 else ml
        out.append((token_lit << 4) | token_ml)
        rem = lit_len - 15
        while rem >= 0:
            out.append(min(rem, 255))
            if rem < 255:
                break
            rem -= 255
        out.extend(data[lit_start:lit_end])
        if match_len:
            out.append(offset & 0xFF)
            out.append(offset >> 8)
            rem = ml - 15
            while rem >= 0:
                out.append(min(rem, 255))
                if rem < 255:
                    break
                rem -= 255

    if n < 13:  # too short for any legal match placement
        emit(0, n, 0, 0)
        return bytes(out)

    table: dict[bytes, int] = {}
    anchor = 0
    i = 0
    limit = n - 12  # no match may start past here
    match_limit = n - 5  # matches must end before the last 5 bytes
    while i < limit:
        key = data[i : i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and data[cand : cand + 4] == key:
            m = i + 4
            c = cand + 4
            while m < match_limit and data[m] == data[c]:
                m += 1
                c += 1
            emit(anchor, i, i - cand, m - i)
            anchor = i = m
            continue
        i += 1
    emit(anchor, n, 0, 0)
    return bytes(out)


def xxh32(data: bytes, seed: int = 0) -> int:
    """xxHash32 (https://github.com/Cyan4973/xxHash/blob/dev/doc/
    xxhash_spec.md) — needed so emitted frame header checksums are
    spec-valid for conformant external readers."""
    P1, P2, P3, P4, P5 = (
        2654435761, 2246822519, 3266489917, 668265263, 374761393,
    )
    M = 0xFFFFFFFF

    def rotl(x: int, r: int) -> int:
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    i = 0
    if n >= 16:
        v1, v2, v3, v4 = (seed + P1 + P2) & M, (seed + P2) & M, seed & M, (seed - P1) & M
        while i + 16 <= n:
            lanes = struct.unpack_from("<IIII", data, i)
            v1 = (rotl((v1 + lanes[0] * P2) & M, 13) * P1) & M
            v2 = (rotl((v2 + lanes[1] * P2) & M, 13) * P1) & M
            v3 = (rotl((v3 + lanes[2] * P2) & M, 13) * P1) & M
            v4 = (rotl((v4 + lanes[3] * P2) & M, 13) * P1) & M
            i += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 4 <= n:
        h = (rotl((h + struct.unpack_from("<I", data, i)[0] * P3) & M, 17) * P4) & M
        i += 4
    while i < n:
        h = (rotl((h + data[i] * P5) & M, 11) * P1) & M
        i += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    h ^= h >> 16
    return h


def compress_frame(data: bytes) -> bytes:
    """Wrap compressed blocks in a minimal standard frame (4MB-max
    blocks, content size present, block/content checksums absent,
    spec-correct header checksum)."""
    out = bytearray(struct.pack("<I", FRAME_MAGIC))
    flg = (1 << 6) | 0x08 | 0x20  # version 1, content size, block indep
    bd = 7 << 4  # 4MB max block
    descriptor = bytes([flg, bd]) + struct.pack("<Q", len(data))
    out += descriptor
    out.append((xxh32(descriptor) >> 8) & 0xFF)
    view = memoryview(data)
    block_cap = 4 << 20
    for start in range(0, len(data), block_cap):
        chunk = bytes(view[start : start + block_cap])
        comp = compress_block(chunk)
        if len(comp) < len(chunk):
            out += struct.pack("<I", len(comp)) + comp
        else:
            out += struct.pack("<I", 0x80000000 | len(chunk)) + chunk
    out += struct.pack("<I", 0)  # EndMark
    return bytes(out)
