"""Vectorized numpy group-max primitives.

``np.maximum.at`` runs an element-wise Python-speed inner loop; these
sort+``reduceat`` equivalents are ~3x faster at cube scale and far
faster over raw rows.  Shared by the star-tree build/traversal
(``startree/``) and the HLL register finalizers (``engine/executor``).
"""
from __future__ import annotations

import numpy as np


def group_max_rows(inverse: np.ndarray, num_groups: int, values: np.ndarray) -> np.ndarray:
    """Per-group elementwise max of [R, M] ``values`` -> [G, M].

    Contract: every group in [0, num_groups) has >= 1 row (callers pass
    ``inverse`` from ``np.unique(..., return_inverse=True)``, which
    guarantees it) — ``reduceat`` over an empty segment would return
    the boundary element, not an identity.  ``scatter_max_2d`` below
    has no such restriction."""
    order = np.argsort(inverse, kind="stable")
    bounds = np.searchsorted(inverse[order], np.arange(num_groups))
    return np.maximum.reduceat(values[order], bounds, axis=0)


def scatter_max_2d(
    inverse: np.ndarray, num_groups: int, cols: np.ndarray, vals: np.ndarray, m: int
) -> np.ndarray:
    """out[g, cols[i]] = max(vals[i]) over rows with inverse[i] == g
    (one (group, col) cell per input row)."""
    if np.asarray(vals).size == 0:
        return np.zeros((num_groups, m), dtype=np.asarray(vals).dtype)
    keys = np.asarray(inverse, dtype=np.int64) * m + cols
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    vs = np.asarray(vals)[order]
    starts = np.nonzero(np.concatenate(([True], ks[1:] != ks[:-1])))[0]
    maxes = np.maximum.reduceat(vs, starts)
    uk = ks[starts]
    out = np.zeros((num_groups, m), dtype=vs.dtype)
    out[uk // m, uk % m] = maxes
    return out
