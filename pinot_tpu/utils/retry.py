"""Retry policies (pinot-common ``common/utils/retry/`` analog:
fixed-delay, exponential-backoff, no-delay)."""
from __future__ import annotations

import time
from typing import Callable, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    pass


class RetryPolicy:
    def __init__(self, max_attempts: int) -> None:
        self.max_attempts = max_attempts

    def delay_s(self, attempt: int) -> float:
        raise NotImplementedError

    def attempt(self, fn: Callable[[], T]) -> T:
        last: Exception | None = None
        for i in range(self.max_attempts):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - policy retries anything
                last = e
                if i + 1 < self.max_attempts:
                    time.sleep(self.delay_s(i))
        raise RetryError(f"failed after {self.max_attempts} attempts: {last}") from last


class NoDelayRetryPolicy(RetryPolicy):
    def delay_s(self, attempt: int) -> float:
        return 0.0


class FixedDelayRetryPolicy(RetryPolicy):
    def __init__(self, max_attempts: int, delay_s: float) -> None:
        super().__init__(max_attempts)
        self._delay = delay_s

    def delay_s(self, attempt: int) -> float:
        return self._delay


class ExponentialBackoffRetryPolicy(RetryPolicy):
    def __init__(self, max_attempts: int, initial_delay_s: float, factor: float = 2.0) -> None:
        super().__init__(max_attempts)
        self.initial = initial_delay_s
        self.factor = factor

    def delay_s(self, attempt: int) -> float:
        return self.initial * (self.factor**attempt)
