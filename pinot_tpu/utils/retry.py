"""Retry policies (pinot-common ``common/utils/retry/`` analog:
fixed-delay, exponential-backoff, no-delay; exponential backoff
supports full jitter so a fleet retrying the same dead dependency
doesn't re-converge on it in lockstep)."""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    pass


class RetryPolicy:
    def __init__(self, max_attempts: int) -> None:
        self.max_attempts = max_attempts

    def delay_s(self, attempt: int) -> float:
        raise NotImplementedError

    def attempt(self, fn: Callable[[], T]) -> T:
        last: Exception | None = None
        for i in range(self.max_attempts):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - policy retries anything
                last = e
                if i + 1 < self.max_attempts:
                    time.sleep(self.delay_s(i))
        raise RetryError(f"failed after {self.max_attempts} attempts: {last}") from last


class NoDelayRetryPolicy(RetryPolicy):
    def delay_s(self, attempt: int) -> float:
        return 0.0


class FixedDelayRetryPolicy(RetryPolicy):
    def __init__(self, max_attempts: int, delay_s: float) -> None:
        super().__init__(max_attempts)
        self._delay = delay_s

    def delay_s(self, attempt: int) -> float:
        return self._delay


class ExponentialBackoffRetryPolicy(RetryPolicy):
    """Exponential backoff, optionally with FULL jitter: each delay is
    drawn uniformly from [0, initial * factor**attempt].  Synchronized
    failures (every replica fetching from a just-restarted controller)
    otherwise retry in lockstep and hammer the recovering dependency at
    exactly the backoff boundaries; jitter spreads the herd.  ``seed``
    makes the draw deterministic for tests."""

    def __init__(
        self,
        max_attempts: int,
        initial_delay_s: float,
        factor: float = 2.0,
        jitter: bool = False,
        seed: Optional[int] = None,
    ) -> None:
        super().__init__(max_attempts)
        self.initial = initial_delay_s
        self.factor = factor
        self._rng = random.Random(seed) if jitter else None

    def delay_s(self, attempt: int) -> float:
        cap = self.initial * (self.factor**attempt)
        if self._rng is not None:
            return self._rng.uniform(0.0, cap)
        return cap


class FullJitterBackoff:
    """Stateful full-jitter backoff for long-lived retry LOOPS (vs the
    bounded-attempt policies above): heartbeat/poll/consumer loops that
    must ride out a dependency outage of unknown length.

    ``next_delay()`` grows the window exponentially up to ``cap_s`` and
    draws uniformly from [floor_s, window] (full jitter: a fleet of
    partitioned consumers must not re-converge on the recovering
    controller in lockstep); ``reset()`` on success re-arms the fast
    first retry.  ``failures`` counts consecutive failures, which the
    callers surface as a ``controller.unreachable`` gauge."""

    def __init__(
        self,
        initial_s: float = 0.25,
        cap_s: float = 5.0,
        factor: float = 2.0,
        floor_s: float = 0.05,
        seed: Optional[int] = None,
    ) -> None:
        self.initial = initial_s
        self.cap = cap_s
        self.factor = factor
        self.floor = floor_s
        self.failures = 0
        self._rng = random.Random(seed)

    def reset(self) -> None:
        self.failures = 0

    def tighten_cap(self, liveness_timeout_s: float) -> float:
        """Keep the worst-case delay well under a liveness window:
        loops whose REQUESTS feed a failure detector (heartbeats) call
        this with the detector's advertised timeout so backoff can
        never push the inter-request gap past it — under an asymmetric
        partition requests still arrive while replies are lost, and a
        deep backoff would flap the live sender dead.  The cap takes a
        THIRD of the window; returns that share so the caller can clamp
        its per-request timeout to the same budget (a blackholed
        request that blocks for urlopen's default 10s would blow the
        window on its own): request timeout + one full backoff delay
        stays at most two thirds of the window.  Tightening only — a
        share above the constructed cap must never LOOSEN it (an
        initial_s bigger than the share would otherwise win the clamp
        and blow the very window this enforces)."""
        share = float(liveness_timeout_s) / 3.0
        self.cap = min(self.cap, max(self.floor, share))
        return share

    def next_delay(self) -> float:
        window = min(self.cap, self.initial * (self.factor ** self.failures))
        self.failures += 1
        return self._rng.uniform(min(self.floor, window), window)


def tighten_liveness_budget(
    backoff: FullJitterBackoff,
    liveness_timeout_s: float,
    request_timeout_s: float,
    floor_s: float = 0.5,
) -> float:
    """One liveness-budget computation for every heartbeating role:
    caps ``backoff`` at a third of the detector's window (see
    ``tighten_cap``) and returns the per-request timeout clamped to the
    same share — the two MUST shrink together, or a blackholed request
    alone can outlast the window the backoff was capped for."""
    share = backoff.tighten_cap(float(liveness_timeout_s))
    return min(request_timeout_s, max(floor_s, share))
