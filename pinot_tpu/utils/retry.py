"""Retry policies (pinot-common ``common/utils/retry/`` analog:
fixed-delay, exponential-backoff, no-delay; exponential backoff
supports full jitter so a fleet retrying the same dead dependency
doesn't re-converge on it in lockstep)."""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    pass


class RetryPolicy:
    def __init__(self, max_attempts: int) -> None:
        self.max_attempts = max_attempts

    def delay_s(self, attempt: int) -> float:
        raise NotImplementedError

    def attempt(self, fn: Callable[[], T]) -> T:
        last: Exception | None = None
        for i in range(self.max_attempts):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - policy retries anything
                last = e
                if i + 1 < self.max_attempts:
                    time.sleep(self.delay_s(i))
        raise RetryError(f"failed after {self.max_attempts} attempts: {last}") from last


class NoDelayRetryPolicy(RetryPolicy):
    def delay_s(self, attempt: int) -> float:
        return 0.0


class FixedDelayRetryPolicy(RetryPolicy):
    def __init__(self, max_attempts: int, delay_s: float) -> None:
        super().__init__(max_attempts)
        self._delay = delay_s

    def delay_s(self, attempt: int) -> float:
        return self._delay


class ExponentialBackoffRetryPolicy(RetryPolicy):
    """Exponential backoff, optionally with FULL jitter: each delay is
    drawn uniformly from [0, initial * factor**attempt].  Synchronized
    failures (every replica fetching from a just-restarted controller)
    otherwise retry in lockstep and hammer the recovering dependency at
    exactly the backoff boundaries; jitter spreads the herd.  ``seed``
    makes the draw deterministic for tests."""

    def __init__(
        self,
        max_attempts: int,
        initial_delay_s: float,
        factor: float = 2.0,
        jitter: bool = False,
        seed: Optional[int] = None,
    ) -> None:
        super().__init__(max_attempts)
        self.initial = initial_delay_s
        self.factor = factor
        self._rng = random.Random(seed) if jitter else None

    def delay_s(self, attempt: int) -> float:
        cap = self.initial * (self.factor**attempt)
        if self._rng is not None:
            return self._rng.uniform(0.0, cap)
        return cap
