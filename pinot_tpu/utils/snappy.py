"""Pure-Python snappy decompression (+ xerial stream framing).

Kafka 0.8-era producers commonly compressed MessageSets with snappy;
no snappy library ships in this image, so the block format
(https://github.com/google/snappy/blob/main/format_description.txt)
is implemented directly: varint uncompressed length, then a tag stream
of literals and back-references.  Kafka wraps snappy in snappy-java's
"xerial" framing (magic header + [uncompressed_len? no — chunked
compressed blocks]); ``decompress`` detects and unwraps it.

Decompression only — the shim and producers in this repo use gzip or
no compression; this exists so consuming from a REAL broker whose
producers chose snappy works instead of failing.
"""
from __future__ import annotations

import struct

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def _decompress_block(data: bytes) -> bytes:
    if not data:
        raise ValueError("snappy: empty block")
    pos = 0
    # varint: uncompressed length
    shift = 0
    length = 0
    while True:
        b = data[pos]
        pos += 1
        length |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:
                extra = ln - 60
                ln = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            out += data[pos : pos + ln]
            pos += ln
            continue
        if kind == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: invalid back-reference")
        start = len(out) - offset
        if offset >= ln:
            out += out[start : start + ln]  # non-overlapping: one slice
        else:
            # overlapping copies are defined byte-by-byte
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != length:
        raise ValueError(f"snappy: length mismatch {len(out)} != {length}")
    return bytes(out)


def decompress(data: bytes) -> bytes:
    """Snappy block data, or a snappy-java (xerial) framed stream of
    blocks as Kafka on-the-wire snappy uses."""
    if data.startswith(_XERIAL_MAGIC):
        pos = len(_XERIAL_MAGIC) + 8  # magic + version + compat ints
        blocks = []
        while pos < len(data):
            (size,) = struct.unpack(">i", data[pos : pos + 4])
            pos += 4
            blocks.append(_decompress_block(data[pos : pos + size]))
            pos += size
        return b"".join(blocks)
    return _decompress_block(data)


# -- compression (for tests / symmetric tooling): all-literal encoding
# is valid snappy, just uncompressed-size — fine for protocol tests.


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def compress(data: bytes) -> bytes:
    """Valid (literal-only) snappy encoding — decodable by any snappy
    implementation; exists for the protocol tests (the shim itself
    emits uncompressed MessageSets)."""
    out = bytearray(_varint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append(ln << 2)
        else:
            out.append(61 << 2)  # tag 61: 2-byte length literal
            out += (ln).to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)
