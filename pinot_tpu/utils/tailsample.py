"""Tail-based trace sampling: keep only the traces worth keeping.

Head sampling (the client's ``trace=true`` flag) can't catch a tail
regression — by the time someone re-runs the slow query with tracing
on, the moment is gone.  Tail sampling inverts it: every query runs
with lightweight tracing ALWAYS ON (the broker arms the span tree for
each request; the overhead is regression-gated by the serving perf
gate's sampling-overhead spec), and the *retention* decision happens at
query completion, when the outcome is known:

- kept when the query was **slow** (``PINOT_TPU_TAIL_SLOW_MS``, default
  250ms), **failed**, or **partial** — the tails an operator pages for;
- plus an unconditional **1-in-N** sample (``PINOT_TPU_TAIL_SAMPLE_N``,
  default 128; 0 disables) so the healthy baseline is represented too.

Retained traces land in a bounded ring (``PINOT_TPU_TAIL_RING_N``,
default 64, oldest evicted), keyed by requestId (the PR 4 querylog
cross-link: slow-log entries carry ``traceRetained``/``traceRef``, and
each tail entry carries the requestId back), and feed a **critical-path
aggregator** keyed by the PR 8 literal-erased plan-shape digest: per
phase SELF time (a span's ms minus its children's — nesting never
double-counts), so ``/debug/tails`` answers "for this shape, tail p99
is 70% laneWait".

ZERO-OVERHEAD CONTRACT on the not-retained path (the
``SPAN_ALLOCATIONS`` analog): the decision reads scalars only, and the
expensive work — merging the per-server span trees, copying spans,
building the ring entry, updating the aggregator — happens ONLY after a
keep decision.  ``TAIL_ALLOCATIONS`` counts every retained-entry build;
tests assert a not-retained query moves it by exactly zero.
``PINOT_TPU_TAIL_TRACE=0`` disables the always-on tracing entirely
(restoring the PR 4 contract that an untraced query allocates no spans
at all).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from pinot_tpu.utils.metrics import interpolated_percentile as _percentile

# module-wide count of retained tail entries ever built — the
# not-retained-path zero-overhead guard (tests assert no delta)
TAIL_ALLOCATIONS = 0

_AGG_WINDOW = 128  # per-digest retained-tail sample window


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def phase_self_ms(scopes: Dict[str, List[Dict[str, Any]]]) -> Dict[str, float]:
    """Merged span scopes -> per-span-name SELF milliseconds.

    Self time = a span's ms minus the sum of its direct children's ms
    (floored at 0 — children overlapping a parent via concurrency must
    not go negative).  Summing self times by span name attributes the
    whole wall once: a 100ms serverQuery holding a 70ms laneWait
    contributes 30 to serverQuery and 70 to laneWait, never 170."""
    spans = [s for span_list in scopes.values() for s in span_list]
    child_ms: Dict[str, float] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None:
            child_ms[parent] = child_ms.get(parent, 0.0) + float(s.get("ms") or 0.0)
    out: Dict[str, float] = {}
    for s in spans:
        ms = float(s.get("ms") or 0.0)
        self_ms = max(0.0, ms - child_ms.get(s.get("id"), 0.0))
        if self_ms <= 0.0:
            continue
        name = s.get("span") or "?"
        out[name] = out.get(name, 0.0) + self_ms
    return {k: round(v, 3) for k, v in out.items()}


class _DigestAgg:
    __slots__ = ("digest", "summary", "table", "tails", "totals", "phases")

    def __init__(self, digest: str, summary: str, table: str) -> None:
        self.digest = digest
        self.summary = summary
        self.table = table
        self.tails = 0
        self.totals: Deque[float] = deque(maxlen=_AGG_WINDOW)
        # per-phase self-ms sums over the SAME retained window: fractions
        # are phase_sum / all_phase_sum, so they add to ~1 by construction
        self.phases: Deque[Dict[str, float]] = deque(maxlen=_AGG_WINDOW)


class TailSampler:
    def __init__(
        self,
        enabled: Optional[bool] = None,
        slow_ms: Optional[float] = None,
        sample_n: Optional[int] = None,
        capacity: Optional[int] = None,
        metrics=None,
    ) -> None:
        if enabled is None:
            enabled = os.environ.get("PINOT_TPU_TAIL_TRACE", "1") != "0"
        self.enabled = enabled
        self.slow_ms = (
            _env_f("PINOT_TPU_TAIL_SLOW_MS", 250.0) if slow_ms is None else slow_ms
        )
        self.sample_n = (
            int(_env_f("PINOT_TPU_TAIL_SAMPLE_N", 128))
            if sample_n is None
            else sample_n
        )
        self.capacity = max(
            1,
            int(_env_f("PINOT_TPU_TAIL_RING_N", 64)) if capacity is None else capacity,
        )
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=self.capacity)
        self._by_digest: Dict[str, _DigestAgg] = {}
        self._seen = 0
        self._lock = threading.Lock()
        self.metrics = metrics
        if metrics is not None:
            metrics.meter("tails.observed")
            metrics.meter("tails.retained")
            metrics.gauge("tails.ring").set_fn(lambda: len(self._ring))

    @property
    def armed(self) -> bool:
        """True when every query should run with the span tree enabled."""
        return self.enabled

    # -- decision (scalar-only: the zero-overhead half) ----------------
    def decide(
        self, time_used_ms: float, failed: bool, partial: bool
    ) -> Optional[str]:
        """Retention verdict for one completed query.  Reads and writes
        scalars only — no dicts, no lists, no span access — so the
        not-retained path costs one lock and two integer ops."""
        with self._lock:
            self._seen += 1
            sampled = self.sample_n > 0 and self._seen % self.sample_n == 0
        if self.metrics is not None:
            self.metrics.meter("tails.observed").mark()
        if failed:
            return "failed"
        if partial:
            return "partial"
        if time_used_ms >= self.slow_ms:
            return "slow"
        if sampled:
            return "sampled"
        return None

    # -- retention (allocates: only reached on a keep verdict) ---------
    def retain(
        self,
        request_id: str,
        reason: str,
        time_used_ms: float,
        scopes: Dict[str, List[Dict[str, Any]]],
        table: str = "",
        plan_digest: str = "",
        summary: str = "",
    ) -> Dict[str, Any]:
        global TAIL_ALLOCATIONS
        phases = phase_self_ms(scopes)
        entry = {
            "requestId": request_id,
            "ts": round(time.time(), 3),
            "reason": reason,
            "timeUsedMs": round(time_used_ms, 3),
            "table": table,
            "planDigest": plan_digest,
            "summary": summary,
            "phaseSelfMs": phases,
            "scopes": scopes,
        }
        with self._lock:
            self._ring.append(entry)
            if plan_digest:
                agg = self._by_digest.get(plan_digest)
                if agg is None:
                    if len(self._by_digest) >= 4 * self.capacity:
                        # bounded like the ring: evict the least-tailed
                        victim = min(
                            self._by_digest.values(), key=lambda a: a.tails
                        )
                        self._by_digest.pop(victim.digest, None)
                    agg = self._by_digest[plan_digest] = _DigestAgg(
                        plan_digest, summary, table
                    )
                agg.tails += 1
                agg.totals.append(float(time_used_ms))
                agg.phases.append(phases)
            TAIL_ALLOCATIONS += 1
        if self.metrics is not None:
            self.metrics.meter("tails.retained").mark()
        return entry

    def observe(
        self,
        request_id: str,
        time_used_ms: float,
        failed: bool,
        partial: bool,
        scopes_fn: Callable[[], Dict[str, List[Dict[str, Any]]]],
        table: str = "",
        plan_digest: str = "",
        summary: str = "",
    ) -> Optional[str]:
        """Decision + conditional retention.  ``scopes_fn`` is called
        ONLY on a keep verdict — the span-tree merge never runs for a
        dropped tail."""
        reason = self.decide(time_used_ms, failed, partial)
        if reason is None:
            return None
        self.retain(
            request_id,
            reason,
            time_used_ms,
            scopes_fn(),
            table=table,
            plan_digest=plan_digest,
            summary=summary,
        )
        return reason

    # -- read side -----------------------------------------------------
    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        """Full retained entry (scopes included) by requestId — the
        ``/debug/queries`` -> ``/debug/tails?requestId=`` hop."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry["requestId"] == request_id:
                    return dict(entry)
        return None

    def _agg_dict(self, a: _DigestAgg) -> Dict[str, Any]:
        totals = sorted(a.totals)
        phase_sums: Dict[str, float] = {}
        for p in a.phases:
            for name, ms in p.items():
                phase_sums[name] = phase_sums.get(name, 0.0) + ms
        all_ms = sum(phase_sums.values())
        attribution = (
            {
                name: round(ms / all_ms, 4)
                for name, ms in sorted(
                    phase_sums.items(), key=lambda kv: -kv[1]
                )
            }
            if all_ms > 0
            else {}
        )
        top = next(iter(attribution), None)
        return {
            "digest": a.digest,
            "summary": a.summary,
            "table": a.table,
            "tails": a.tails,
            "windowTails": len(totals),
            "latencyMs": {
                "p50": round(_percentile(totals, 50), 3),
                "p99": round(_percentile(totals, 99), 3),
            },
            "phaseMs": {k: round(v, 3) for k, v in phase_sums.items()},
            "attribution": attribution,
            "topPhase": top,
        }

    def snapshot(
        self, top: int = 20, include_traces: bool = False
    ) -> Dict[str, Any]:
        """``/debug/tails`` payload: config + the retained ring (newest
        first, span trees elided unless asked — they are fetchable per
        requestId) + the per-digest tail attribution, worst p99 first."""
        with self._lock:
            entries = [dict(e) for e in reversed(self._ring)]
            aggs = [self._agg_dict(a) for a in self._by_digest.values()]
            seen = self._seen
        if not include_traces:
            for e in entries:
                e.pop("scopes", None)
        aggs.sort(key=lambda d: -d["latencyMs"]["p99"])
        return {
            "enabled": self.enabled,
            "slowMs": self.slow_ms,
            "sampleN": self.sample_n,
            "capacity": self.capacity,
            "observed": seen,
            "retained": len(entries),
            "entries": entries,
            "byDigest": aggs[: max(1, top)],
        }
