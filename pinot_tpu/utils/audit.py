"""Continuous correctness audit plane (ISSUE 19).

Wrong answers are the failure mode the self-healing ladder (PR 3) can
NEVER catch: a device tier that returns a plausible-but-incorrect
payload raises nothing, so retry/failover/poison all stay silent and the
error ships to the client.  This module closes that gap with two
background samplers that re-derive ground truth and compare:

- ``ShadowAuditor`` (server-side): re-executes a seeded 1-in-N sample of
  completed production queries against the always-correct host oracle
  (``QueryExecutor.execute_host_oracle``) over the EXACT views the
  production reply served (``query_view()`` snapshots pin mutable
  segments at their row watermark, so the re-execution sees the same
  staged generation; the result cache is bypassed by construction).
  Payloads are compared after stripping accounting (the PR 3
  differential contract — ``numDocsScanned`` etc. legitimately differ
  per tier) with a bounded numeric tolerance (``payloads_equivalent``):
  a float32 device sum and the float64 host oracle honestly wobble with
  accumulation order.  A divergence increments ``audit.divergences``,
  dumps a
  flight-recorder bundle carrying both payloads + tier/residency state,
  and quarantines the (plan digest, tier) via the executor's poison map
  so the lying tier stops serving that shape.

- ``ReplicaAuditor`` (broker-side): occasionally re-issues a sampled
  query's first batch to BOTH the original server and an alternate
  covering replica and compares the (accounting-stripped) reduced
  payloads — the replica-divergence detector.  Restricted to
  non-realtime physical tables: realtime replicas consume independently,
  so an offset-drift "divergence" would be noise, not corruption.

Both samplers draw from ONE process-wide token budget
(``PINOT_TPU_AUDIT_BUDGET_PER_S``), so the audit plane's total overhead
is bounded regardless of how many tables/brokers sample.  The work
itself runs on background worker threads modeled on
``server/prewarm.py`` — bounded queue, drop-don't-block, never on the
serving path.

Knobs:

- ``PINOT_TPU_AUDIT_SAMPLE_N``    shadow sample rate (1-in-N completed
                                  queries), default 64; 0 disables.
- ``PINOT_TPU_AUDIT_REPLICA_N``   replica sample rate, default 256;
                                  0 disables.
- ``PINOT_TPU_AUDIT_BUDGET_PER_S``shared token budget, default 8/s.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# every auditor that ever started a thread, for the test-suite leak
# guard (same contract as prewarm._workers: only a STOPPED auditor
# whose thread survives is a leak)
_workers: List[Any] = []
_workers_lock = threading.Lock()


def leaked_audit_threads(grace_s: float = 2.0) -> List[str]:
    """Names of audit threads of STOPPED auditors still alive after
    ``grace_s`` of joining (conftest guard)."""
    deadline = time.monotonic() + grace_s
    leaked: List[str] = []
    with _workers_lock:
        workers = list(_workers)
    for w in workers:
        t = w._thread
        if t is None or not w._stop.is_set():
            continue
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            leaked.append(t.name)
    return leaked


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SamplerBudget:
    """Token bucket shared by EVERY sampler in the process: the "one
    sampler budget" that bounds total audit overhead.  ``take()`` is a
    non-blocking permit check — a sample denied a token is simply not
    audited (counted by the caller as dropped), never queued."""

    def __init__(self, per_s: Optional[float] = None, burst: float = 4.0) -> None:
        self.per_s = (
            per_s
            if per_s is not None
            else _env_float("PINOT_TPU_AUDIT_BUDGET_PER_S", 8.0)
        )
        self.burst = max(1.0, burst)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        if self.per_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.per_s
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


# THE shared budget (both auditors in a process draw from it; tests may
# swap in private instances)
BUDGET = SamplerBudget()


# accounting fields every byte-identity differential strips (the PR 3
# contract, extended with freshnessMs): wall-clock, per-tier work
# counters, and scatter topology legitimately differ between a
# production tier and the host oracle / an alternate replica — the DATA
# fields (selection rows, aggregation values, totalDocs, exceptions)
# must not.
ACCOUNTING_FIELDS = (
    "timeUsedMs",
    "requestId",
    "cost",
    "numDocsScanned",
    "numEntriesScannedInFilter",
    "numEntriesScannedPostFilter",
    "numSegmentsQueried",
    "numServersQueried",
    "numServersResponded",
    "numSegmentsUnserved",
    "partialResponse",
    "numRetries",
    "numHedges",
    "freshnessMs",
    "planDigest",
    "traceInfo",
    "explain",
)


def strip_accounting(payload: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(payload)
    for key in ACCOUNTING_FIELDS:
        out.pop(key, None)
    return out


def canonical_payload(request, result) -> Dict[str, Any]:
    """One IntermediateResult -> the comparable client payload: run the
    REAL broker reduce over it (so formatting, trimming, and ordering
    are exactly what a client would see), then strip accounting."""
    from pinot_tpu.engine.reduce import reduce_to_response

    return strip_accounting(reduce_to_response(request, [result], []).to_json())


def _as_number(x: Any) -> Optional[float]:
    if isinstance(x, bool):
        return None
    if isinstance(x, (int, float)):
        return float(x)
    if isinstance(x, str):
        try:
            return float(x)
        except ValueError:
            return None
    return None


def payloads_equivalent(
    a: Any, b: Any, rel_tol: float = 5e-4, abs_tol: float = 1e-3
) -> bool:
    """Structural payload equality with a numeric tolerance on leaves,
    exact everywhere else.

    Why not byte identity: a float32 device sum and the float64 host
    oracle legitimately disagree (accumulation order + precision), and
    byte-comparing the formatted values would quarantine healthy tiers.
    The tolerance is sized for float32 tree-reduction noise at real scan
    sizes — relative error grows ~sqrt(n)·eps, so a 10M-row sum honestly
    wobbles ~2e-4; 5e-4 covers that with margin (an earlier 1e-5 draft
    false-positived on a clean 1M-row Q1 sum and quarantined the healthy
    device tier).  Genuine wrong answers — a corrupted tier, a dropped
    segment, a stale replica — shift aggregates by whole values, orders
    of magnitude above the band, and the exact-aggregate contract (ints,
    min/max, counts) still compares exactly: identical values are always
    close.  Structure, keys, ordering, group labels, and non-numeric
    strings remain byte-exact."""
    if isinstance(a, dict) and isinstance(b, dict):
        if a.keys() != b.keys():
            return False
        return all(
            payloads_equivalent(a[k], b[k], rel_tol, abs_tol) for k in a
        )
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        return all(
            payloads_equivalent(x, y, rel_tol, abs_tol)
            for x, y in zip(a, b)
        )
    if a == b:
        return True
    na, nb = _as_number(a), _as_number(b)
    if na is None or nb is None:
        return False
    import math

    return math.isclose(na, nb, rel_tol=rel_tol, abs_tol=abs_tol)


# ---------------------------------------------------------------------------
# Server-side shadow differential auditor
# ---------------------------------------------------------------------------


class ShadowAuditor:
    """Background differential checker for one ``ServerInstance``.

    ``offer()`` is the serving-path hook (``_process_traced``, after a
    successful execution): a deterministic 1-in-N counter plus the
    shared token budget decide whether the completed query is queued
    for shadow re-execution.  Holding the offered ``views`` pins the
    exact snapshot production served; the worker replays the request on
    the host oracle and compares canonical payloads."""

    _QUEUE_MAX = 16
    _DIVERGENCE_RING = 16

    def __init__(
        self,
        instance,
        sample_n: Optional[int] = None,
        budget: Optional[SamplerBudget] = None,
    ) -> None:
        self.instance = instance
        self.sample_n = (
            sample_n
            if sample_n is not None
            else _env_int("PINOT_TPU_AUDIT_SAMPLE_N", 64)
        )
        self.budget = budget if budget is not None else BUDGET
        self.metrics = instance.metrics
        for m in (
            "audit.samples", "audit.divergences", "audit.dropped",
            "audit.errors", "audit.quarantines",
        ):
            self.metrics.meter(m)
        self._count = 0
        self._queue: deque = deque()
        self._divergences: deque = deque(maxlen=self._DIVERGENCE_RING)
        self._trigger = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.metrics.gauge("audit.queueDepth").set_fn(lambda: len(self._queue))

    @property
    def enabled(self) -> bool:
        return self.sample_n > 0

    # -- serving-path hook (must stay cheap) ---------------------------
    def offer(self, req: dict, request, views, result) -> bool:
        """Called inline after a successful non-explain, non-join
        execution.  The fast path is one counter increment; only the
        1-in-N winners pay the budget check and enqueue."""
        if not self.enabled or self._stop.is_set():
            return False
        self._count += 1
        if self._count % self.sample_n:
            return False
        if (
            result.exceptions
            or request.explain
            or request.join is not None
            or getattr(result, "_served_tier", None) in (None, "host")
        ):
            # host-served replies ARE the oracle — re-checking them
            # could only burn budget agreeing with itself
            return False
        if not self.budget.take():
            self.metrics.meter("audit.dropped").mark()
            return False
        job = {
            "requestId": str(req.get("requestId") or ""),
            "table": req.get("table", ""),
            "request": request,
            "views": list(views),
            "result": result,
            "enqueuedAt": time.monotonic(),
        }
        with self._lock:
            if len(self._queue) >= self._QUEUE_MAX:
                self.metrics.meter("audit.dropped").mark()
                return False
            self._queue.append(job)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run,
                    name=f"audit-{self.instance.name}",
                    daemon=True,
                )
                with _workers_lock:
                    _workers.append(self)
                self._thread.start()
        self._trigger.set()
        return True

    def stop(self) -> None:
        self._stop.set()
        self._trigger.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        with self._lock:
            self._queue.clear()

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._trigger.wait(timeout=0.5):
                continue
            self._trigger.clear()
            while not self._stop.is_set():
                with self._lock:
                    job = self._queue.popleft() if self._queue else None
                if job is None:
                    break
                try:
                    self._audit_one(job)
                except Exception:
                    # a sick audit must never kill the worker — one
                    # sample is lost, the next drains normally
                    logger.exception("shadow audit failed")
                    self.metrics.meter("audit.errors").mark()

    def _audit_one(self, job: dict) -> None:
        request = job["request"]
        t0 = time.perf_counter()
        oracle = self.instance.executor.execute_host_oracle(
            job["views"], request
        )
        self.metrics.timer("audit.shadowMs").update(
            (time.perf_counter() - t0) * 1000.0
        )
        self.metrics.meter("audit.samples").mark()
        produced = canonical_payload(request, job["result"])
        expected = canonical_payload(request, oracle)
        if payloads_equivalent(produced, expected):
            return
        # -- divergence: the device (or an optimization tier) lied -----
        from pinot_tpu.engine.plandigest import plan_shape_digest

        digest = plan_shape_digest(request)
        tier = getattr(job["result"], "_served_tier", "unknown")
        detect_ms = (time.monotonic() - job["enqueuedAt"]) * 1000.0
        self.metrics.meter("audit.divergences").mark()
        self.metrics.meter("audit.quarantines").mark()
        self.metrics.timer("audit.detectMs").update(detect_ms)
        self.instance.executor.audit_quarantine(
            digest, tier, f"shadow differential mismatch ({job['requestId']})"
        )
        record = {
            "requestId": job["requestId"],
            "table": job["table"],
            "planDigest": digest,
            "tier": tier,
            "detectMs": round(detect_ms, 3),
            "ts": round(time.time(), 3),
        }
        self._divergences.append(record)
        logger.warning(
            "AUDIT DIVERGENCE: tier %s served a wrong answer for shape %s "
            "(request %s) — quarantined", tier, digest, job["requestId"],
        )
        self.instance.flightrec.maybe_dump(
            "auditDivergence",
            {
                **record,
                "producedPayload": produced,
                "expectedPayload": expected,
            },
        )

    # -- observability -------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "sampleN": self.sample_n,
            "budgetPerS": self.budget.per_s,
            "offered": self._count,
            "samples": self.metrics.meter("audit.samples").count,
            "divergences": self.metrics.meter("audit.divergences").count,
            "dropped": self.metrics.meter("audit.dropped").count,
            "errors": self.metrics.meter("audit.errors").count,
            "queueDepth": len(self._queue),
            "recentDivergences": list(self._divergences),
            "quarantined": self.instance.executor.audit_quarantined_snapshot(),
        }


# ---------------------------------------------------------------------------
# Broker-side replica divergence auditor
# ---------------------------------------------------------------------------


class ReplicaAuditor:
    """Background replica cross-checker for one broker.

    ``offer()`` samples completed, successful, non-join, non-explain,
    non-partial queries; the worker re-issues the query's FIRST batch
    to both the original server and an alternate covering replica and
    compares the reduced, accounting-stripped payloads.  Realtime
    physical tables are excluded — their replicas consume the stream
    independently, so honest offset drift would read as divergence."""

    _QUEUE_MAX = 8
    _DIVERGENCE_RING = 16

    def __init__(
        self,
        broker,
        sample_n: Optional[int] = None,
        budget: Optional[SamplerBudget] = None,
    ) -> None:
        self.broker = broker
        self.sample_n = (
            sample_n
            if sample_n is not None
            else _env_int("PINOT_TPU_AUDIT_REPLICA_N", 256)
        )
        self.budget = budget if budget is not None else BUDGET
        self.metrics = broker.metrics
        for m in (
            "audit.replicaChecks", "audit.replicaDivergences",
            "audit.replicaDropped", "audit.replicaErrors",
        ):
            self.metrics.meter(m)
        self._count = 0
        self._queue: deque = deque()
        self._divergences: deque = deque(maxlen=self._DIVERGENCE_RING)
        self._trigger = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.sample_n > 0

    def offer(
        self,
        request,
        batches,
        request_id: str,
        timeout_ms: float,
        resp,
    ) -> bool:
        """Serving-path hook (end of ``_handle_admitted``): cheap
        counter first, then eligibility, then the shared budget."""
        if not self.enabled or self._stop.is_set() or not batches:
            return False
        self._count += 1
        if self._count % self.sample_n:
            return False
        if (
            request.explain
            or request.join is not None
            or resp.exceptions
            or resp.partial_response
        ):
            return False
        batch = batches[0]
        if batch.table.endswith("_REALTIME"):
            return False
        if not self.broker.routing.has_alternate(
            batch.table, list(batch.segments), {batch.server}
        ):
            return False  # replication factor 1: nothing to cross-check
        if not self.budget.take():
            self.metrics.meter("audit.replicaDropped").mark()
            return False
        job = {
            "requestId": request_id,
            "table": batch.table,
            "pql": batch.pql,
            "segments": list(batch.segments),
            "server": batch.server,
            "timeoutMs": float(timeout_ms),
            "enqueuedAt": time.monotonic(),
        }
        with self._lock:
            if len(self._queue) >= self._QUEUE_MAX:
                self.metrics.meter("audit.replicaDropped").mark()
                return False
            self._queue.append(job)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run,
                    name=f"replica-audit-{self.broker.name}",
                    daemon=True,
                )
                with _workers_lock:
                    _workers.append(self)
                self._thread.start()
        self._trigger.set()
        return True

    def stop(self) -> None:
        self._stop.set()
        self._trigger.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        with self._lock:
            self._queue.clear()

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._trigger.wait(timeout=0.5):
                continue
            self._trigger.clear()
            while not self._stop.is_set():
                with self._lock:
                    job = self._queue.popleft() if self._queue else None
                if job is None:
                    break
                try:
                    self._audit_one(job)
                except Exception:
                    logger.exception("replica audit failed")
                    self.metrics.meter("audit.replicaErrors").mark()

    def _reduced(self, request, parts) -> Dict[str, Any]:
        from pinot_tpu.engine.reduce import reduce_to_response

        return strip_accounting(reduce_to_response(request, parts, []).to_json())

    def _audit_one(self, job: dict) -> None:
        from pinot_tpu.pql import optimize_request, parse_pql

        request = optimize_request(parse_pql(job["pql"]))
        assignment, leftover = self.broker.routing.alternates(
            job["table"], job["segments"], {job["server"]}
        )
        if leftover or not assignment:
            return  # the alternate cover evaporated since the offer
        aid = f"{job['requestId']}-raudit"
        primary = self.broker._send_one(
            job["server"], job["table"], job["pql"], job["segments"],
            trace=False, debug_options=None, timeout_ms=job["timeoutMs"],
            attempt_timeout_ms=None, request_id=f"{aid}-p",
        )
        alternates = [
            self.broker._send_one(
                server, job["table"], job["pql"], list(segments),
                trace=False, debug_options=None, timeout_ms=job["timeoutMs"],
                attempt_timeout_ms=None, request_id=f"{aid}-a",
            )
            for server, segments in sorted(assignment.items())
        ]
        if primary.exceptions or any(a.exceptions for a in alternates):
            return  # an errored re-issue proves nothing about data
        self.metrics.meter("audit.replicaChecks").mark()
        lhs = self._reduced(request, [primary])
        rhs = self._reduced(request, alternates)
        divergent = not payloads_equivalent(lhs, rhs)
        record = {
            "requestId": job["requestId"],
            "table": job["table"],
            "server": job["server"],
            "alternates": sorted(assignment),
            "divergent": divergent,
            "detectMs": round(
                (time.monotonic() - job["enqueuedAt"]) * 1000.0, 3
            ),
            "ts": round(time.time(), 3),
        }
        # cross-link: the slow-query log entry (when recorded) gains the
        # audit verdict, so /debug/queries answers "was this checked?"
        self.broker.querylog.annotate(
            job["requestId"], auditRef={"type": "replica", "divergent": divergent}
        )
        if not divergent:
            return
        self.metrics.meter("audit.replicaDivergences").mark()
        self._divergences.append(record)
        logger.warning(
            "REPLICA DIVERGENCE: %s vs %s disagree on table %s (request %s)",
            job["server"], sorted(assignment), job["table"], job["requestId"],
        )
        self.broker.flightrec.maybe_dump(
            "replicaDivergence",
            {**record, "primaryPayload": lhs, "alternatePayload": rhs},
        )

    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "sampleN": self.sample_n,
            "budgetPerS": self.budget.per_s,
            "offered": self._count,
            "checks": self.metrics.meter("audit.replicaChecks").count,
            "divergences": self.metrics.meter("audit.replicaDivergences").count,
            "dropped": self.metrics.meter("audit.replicaDropped").count,
            "errors": self.metrics.meter("audit.replicaErrors").count,
            "queueDepth": len(self._queue),
            "recentDivergences": list(self._divergences),
        }
