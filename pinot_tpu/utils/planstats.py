"""Per-plan-digest workload statistics registry.

One rolling entry per plan SHAPE (``engine/plandigest.py`` — literals
erased), accumulating: execution count, a latency sample window (for
percentiles), the additive cost-vector sums (so per-digest tier mixes
reconcile exactly with the cost meters), coalesce/shed/failure counts,
and first/last-seen timestamps.

Two deployments of the same class:

- **server** (``ServerInstance.plan_stats``): records every executed
  instance request; served at ``/debug/plans`` and in ``status()``.
- **broker** (``BrokerRequestHandler.planstats``): records every merged
  response; served at ``/debug/workload`` as top-K by frequency and by
  cost — the direct input to the ROADMAP's "which plan shapes should we
  batch?" question (cross-query batched serving wants the highest
  frequency x cost shapes first).

Plain EXPLAIN queries are never recorded (they execute nothing);
EXPLAIN ANALYZE is (it did the work).  Eviction is least-recently-seen
beyond ``capacity`` — a bounded registry, not a log.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from pinot_tpu.utils.metrics import interpolated_percentile as _percentile

_LAT_WINDOW = 256  # latency samples kept per digest


class _Entry:
    __slots__ = (
        "digest", "summary", "table", "count", "shed_count", "failed_count",
        "coalesce_hits", "docs_scanned", "cost", "latency", "first_seen",
        "last_seen", "device_lat", "host_lat", "device_execs", "device_info",
        "exemplar",
    )

    def __init__(self, digest: str, summary: str, table: str, now: float) -> None:
        self.digest = digest
        self.summary = summary
        self.table = table
        # one representative query text per shape (first writer wins,
        # bounded): literals are erased from the digest, so ANY member
        # query re-parses to the digest's exact plan shape — this is
        # what lets a prewarming server rebuild and compile the shape
        # without ever having served it (r16 warm-start plane)
        self.exemplar = ""
        self.count = 0
        self.shed_count = 0
        self.failed_count = 0
        self.coalesce_hits = 0
        self.docs_scanned = 0
        self.cost: Dict[str, float] = {}
        self.latency: Deque[float] = deque(maxlen=_LAT_WINDOW)
        # per-tier execution-time windows (utilization plane): device
        # kernel ms and host-path ms recorded separately so /debug/plans
        # tier mixes carry comparable latency on BOTH tiers
        self.device_lat: Deque[float] = deque(maxlen=_LAT_WINDOW)
        self.host_lat: Deque[float] = deque(maxlen=_LAT_WINDOW)
        # unbounded device-tier exec count (device_lat is a capped
        # sample window): the flops multiplier must track the same
        # accumulation horizon as e.cost["deviceMs"], and must NOT
        # count host-fallback/shed/failed queries that ran no kernel
        self.device_execs = 0
        # device-plan identity + static cost analysis (last writer
        # wins): {"digest", "flops", "bytesAccessed", ...} or None
        self.device_info: Optional[Dict[str, Any]] = None
        self.first_seen = now
        self.last_seen = now


class PlanStatsStore:
    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(8, capacity)
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self.total_recorded = 0

    @staticmethod
    def _tier_latency(samples) -> Dict[str, Any]:
        s = sorted(samples)
        return {
            "p50Ms": round(_percentile(s, 50), 3),
            "p95Ms": round(_percentile(s, 95), 3),
            "samples": len(s),
        }

    @staticmethod
    def _roofline(e: "_Entry") -> Optional[Dict[str, Any]]:
        """Achieved-vs-peak roofline for one plan shape: measured device
        wall ms (the SAME deviceMs the phase timers / cost vector
        report) under the bytes the kernel read and the static flops
        the lane's cost analysis declared.  None when the shape never
        ran on device.  Coalesced waiters each record their own fetch
        window, so the sums are per-QUERY attribution, not raw device
        seconds — consistent with every other cost-vector surface."""
        dev_ms = float(e.cost.get("deviceMs", 0) or 0)
        # device_execs is only set by the SERVER store (record(device_ms=...)
        # on a locally measured launch); the broker records fleet-MERGED
        # cost vectors, and a sum-over-servers rate divided by THIS
        # process's platform peak is not a roofline — skip it there
        if dev_ms <= 0 or not e.device_execs:
            return None
        dev_bytes = float(e.cost.get("deviceBytes", 0) or 0)
        out: Dict[str, Any] = {
            "deviceMs": round(dev_ms, 3),
            "deviceBytes": int(dev_bytes),
            "achievedBytesPerSec": round(dev_bytes * 1000.0 / dev_ms, 3),
        }
        info = e.device_info or {}
        if info.get("digest"):
            out["deviceDigest"] = info["digest"]
        flops = info.get("flops")
        if isinstance(flops, (int, float)) and flops > 0 and e.device_execs:
            out["staticFlopsPerExec"] = float(flops)
            # multiplier is DEVICE execs only: a mixed-tier shape's host
            # queries add to e.count but execute zero kernel flops
            out["achievedFlopsPerSec"] = round(
                float(flops) * e.device_execs * 1000.0 / dev_ms, 3
            )
        if isinstance(info.get("bytesAccessed"), (int, float)):
            out["staticBytesPerExec"] = float(info["bytesAccessed"])
        from pinot_tpu.utils.platform import roofline_fractions

        out.update(
            roofline_fractions(
                out["achievedBytesPerSec"], out.get("achievedFlopsPerSec")
            )
        )
        return out

    # -- write side ----------------------------------------------------
    def record(
        self,
        digest: str,
        summary: str = "",
        table: str = "",
        latency_ms: float = 0.0,
        cost: Optional[Dict[str, float]] = None,
        num_docs: int = 0,
        shed: bool = False,
        failed: bool = False,
        device_ms: Optional[float] = None,
        host_ms: Optional[float] = None,
        device_info: Optional[Dict[str, Any]] = None,
        pql: str = "",
    ) -> None:
        now = time.time()
        with self._lock:
            e = self._entries.get(digest)
            if e is None:
                if len(self._entries) >= self.capacity:
                    # evict least-recently-seen: the workload head stays
                    victim = min(self._entries.values(), key=lambda x: x.last_seen)
                    self._entries.pop(victim.digest, None)
                e = self._entries[digest] = _Entry(digest, summary, table, now)
            if summary and not e.summary:
                e.summary = summary
            if table and not e.table:
                e.table = table
            if pql and not e.exemplar:
                e.exemplar = str(pql)[:2048]
            e.last_seen = now
            self.total_recorded += 1
            if shed:
                e.shed_count += 1
                return
            e.count += 1
            if failed:
                e.failed_count += 1
            e.latency.append(float(latency_ms))
            if device_ms:
                e.device_lat.append(float(device_ms))
                e.device_execs += 1
            if host_ms:
                e.host_lat.append(float(host_ms))
            if device_info is not None:
                e.device_info = dict(device_info)
            e.docs_scanned += int(num_docs)
            for k, v in (cost or {}).items():
                e.cost[k] = e.cost.get(k, 0) + v
            if (cost or {}).get("coalesceHits"):
                e.coalesce_hits += int(cost["coalesceHits"])

    # -- read side -----------------------------------------------------
    def digest_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def _entry_dict(self, e: _Entry) -> Dict[str, Any]:
        lat = sorted(e.latency)
        per_query_cost = {
            k: round(v / e.count, 3) if e.count else 0 for k, v in e.cost.items()
        }
        # tier mix straight from the additive cost sums: reconciles with
        # the cost-vector tier counters by construction
        tier_mix = {
            k: int(v) for k, v in e.cost.items() if k.startswith("segments")
        }
        return {
            "digest": e.digest,
            "summary": e.summary,
            "table": e.table,
            "exemplarPql": e.exemplar,
            "count": e.count,
            "shedCount": e.shed_count,
            "failedCount": e.failed_count,
            "coalesceHits": e.coalesce_hits,
            "docsScanned": e.docs_scanned,
            "cost": {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in sorted(e.cost.items())
            },
            "tierMix": tier_mix,
            "perQueryCost": per_query_cost,
            "latencyMs": {
                "p50": round(_percentile(lat, 50), 3),
                "p95": round(_percentile(lat, 95), 3),
                "p99": round(_percentile(lat, 99), 3),
                "samples": len(lat),
            },
            # per-tier execution time so a shape's device vs host cost
            # reads side by side (the tier-mix comparability contract)
            "tierLatencyMs": {
                "device": self._tier_latency(e.device_lat),
                "host": self._tier_latency(e.host_lat),
            },
            # cross-query batching + result-cache per-shape view (from
            # the additive cost sums, so it reconciles with the merged
            # cost vectors by construction): batchRate answers "does
            # this shape actually batch?" on /debug/plans and the
            # broker's /debug/workload top-K
            "batching": self._batching(e),
            "roofline": self._roofline(e),
            "firstSeen": round(e.first_seen, 3),
            "lastSeen": round(e.last_seen, 3),
        }

    @staticmethod
    def _batching(e: _Entry) -> Dict[str, Any]:
        batched = int(e.cost.get("batchHits", 0) or 0)
        cached = int(e.cost.get("rescacheHits", 0) or 0)
        n = max(e.count, 1)
        return {
            "batchedQueries": batched,
            "batchRate": round(batched / n, 4),
            "cacheHits": cached,
            "cacheHitRate": round(cached / n, 4),
        }

    @staticmethod
    def _cost_key(d: Dict[str, Any]) -> float:
        c = d.get("cost") or {}
        # total work proxy: bytes + ms-weighted kernel time; the ROADMAP
        # batching question ranks by frequency x unit cost, both served
        return float(c.get("bytesScanned", 0)) + 1e6 * (
            float(c.get("deviceMs", 0)) + float(c.get("hostMs", 0))
        )

    def top(
        self, k: int = 20, by: str = "count", tables=None
    ) -> List[Dict[str, Any]]:
        # record() sits on the per-query response path and shares this
        # lock, so the O(digests) ranking runs on cheap scalar keys and
        # the expensive dicts (percentiles over the sample window) are
        # built only for the k survivors
        if tables is not None:
            # physical-suffix-insensitive: a prewarming server asks with
            # the raw names it hosts, the broker records logical names
            from pinot_tpu.engine.plandigest import _raw_table

            wanted = {_raw_table(t) for t in tables}
        with self._lock:
            entries = [
                e
                for e in self._entries.values()
                if tables is None or _raw_table(e.table) in wanted
            ]
            if by == "cost":
                keyed = [
                    (self._cost_key({"cost": e.cost}), e) for e in entries
                ]
            else:
                keyed = [((e.count, e.last_seen), e) for e in entries]
        keyed.sort(key=lambda pair: pair[0], reverse=True)
        survivors = [e for _, e in keyed[:k]]
        with self._lock:
            return [
                self._entry_dict(e)
                for e in survivors
                if self._entries.get(e.digest) is e  # evicted between locks
            ]

    def get(self, digest: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._entries.get(digest)
            return self._entry_dict(e) if e is not None else None

    def estimate(self, digest: str) -> Optional[Dict[str, Any]]:
        """Historical per-query estimate for EXPLAIN's estimatedCost:
        mean cost vector + latency percentiles over the rolling window,
        or None when this shape has never executed here."""
        with self._lock:
            e = self._entries.get(digest)
            if e is None or e.count == 0:
                return None
            lat = sorted(e.latency)
            out = {
                "execCount": e.count,
                "latencyP50Ms": round(_percentile(lat, 50), 3),
                "latencyP95Ms": round(_percentile(lat, 95), 3),
                "perQuery": {
                    k: round(v / e.count, 3) for k, v in sorted(e.cost.items())
                },
            }
            # achieved utilization for shapes that ran on device — rides
            # into EXPLAIN's history estimate so explain_dump can render
            # the roofline footer next to the static flops/bytes
            roof = self._roofline(e)
            if roof is not None:
                out["roofline"] = roof
            return out

    def snapshot(self, top: int = 50, by: str = "count") -> Dict[str, Any]:
        return {
            "digests": self.digest_count(),
            "totalRecorded": self.total_recorded,
            "capacity": self.capacity,
            "orderedBy": by,
            "plans": self.top(top, by=by),
        }
