from pinot_tpu.server.datamanager import InstanceDataManager, TableDataManager, SegmentDataManager
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.scheduler import QueryScheduler

__all__ = [
    "InstanceDataManager",
    "TableDataManager",
    "SegmentDataManager",
    "ServerInstance",
    "QueryScheduler",
]
