"""Query scheduler: bounded FCFS pool + bounded pending queue in front
of the executor.

The reference bounds query concurrency with runner/worker pools
(``QueryScheduler.java:35``, ``FCFSQueryScheduler``); queries beyond
pool capacity wait FCFS, and the serving bar is what happens at
saturation.  Device execution is serialized per chip anyway, so the
pool here mainly bounds host-side planning/finalize concurrency and
provides the submit/timeout surface.  The OVERLOAD POLICY (r5): at most
``max_pending`` queries may be queued-or-running; beyond that submits
are shed immediately with ``SchedulerSaturatedError`` rather than
queued without bound — a fast 210-coded error reply beats a timeout
that arrives after the client gave up, and bounds server memory under
a flood (the reference's analog is its scheduler resource limits).
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Callable


class SchedulerSaturatedError(RuntimeError):
    """Raised on submit when the pending queue is at capacity (shed)."""


class QueryScheduler:
    def __init__(self, num_workers: int = 4, max_pending: int = 64) -> None:
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=num_workers)
        self._max_pending = max_pending
        self._pending = 0  # queued + running
        self._shed = 0
        self._lock = threading.Lock()

    @property
    def pending(self) -> int:
        return self._pending

    @property
    def shed_count(self) -> int:
        return self._shed

    def submit(self, fn: Callable[[], Any]) -> concurrent.futures.Future:
        with self._lock:
            if self._pending >= self._max_pending:
                self._shed += 1
                raise SchedulerSaturatedError(
                    f"scheduler saturated: {self._pending} pending >= "
                    f"{self._max_pending} cap"
                )
            self._pending += 1
        try:
            fut = self._pool.submit(fn)
        except BaseException:
            with self._lock:
                self._pending -= 1
            raise

        def _done(_f) -> None:
            with self._lock:
                self._pending -= 1

        fut.add_done_callback(_done)
        return fut

    def run(self, fn: Callable[[], Any], timeout_s: float) -> Any:
        fut = self.submit(fn)
        try:
            return fut.result(timeout=timeout_s)
        except concurrent.futures.TimeoutError:
            # the client is gone: a still-QUEUED query cancels (its
            # done-callback frees the pending slot immediately) so
            # abandoned work cannot pin the scheduler at max_pending
            # and shed live traffic; a RUNNING one must drain
            fut.cancel()
            raise

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
