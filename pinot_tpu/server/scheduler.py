"""Query scheduler: per-table weighted fair-share queues in front of
the executor.

The reference bounds query concurrency with runner/worker pools
(``QueryScheduler.java:35``, ``FCFSQueryScheduler``) and offers
table-aware variants (``TableBasedSchedulerGroupMapper`` +
resource-limited scheduler).  The r5 version here was ONE global FCFS
queue with a single ``max_pending`` bound — correct under uniform load,
but one flooding tenant could fill all 64 slots and starve every other
table behind a wall of its own queries.

FAIR-SHARE POLICY (r7): each table gets its own FCFS queue; workers
dequeue by deficit-round-robin over the active (non-empty) queues, so
a table with weight ``w`` drains ``w`` queries per DRR cycle no matter
how deep another table's queue is.  Admission is work-conserving:

- total queued-or-running is still bounded by ``max_pending`` — beyond
  it submits shed immediately with ``SchedulerSaturatedError`` (210);
- a table alone on the server may fill the whole ``max_pending``
  (idle capacity is never wasted); but when OTHER tables hold pending
  work, a table cannot occupy more than its weighted share
  ``max_pending * w / W_active`` — submits beyond that shed with the
  same typed 210 (per-queue saturation: the error names the queue, and
  the broker fails over to a replica that may have room).

DEADLINE PROPAGATION: unchanged from r5 — the broker serializes its
*remaining* budget into each (re-)issued InstanceRequest and ``run``
pins it as a monotonic deadline checked at worker-dequeue time
(``QueryAbandonedError``).  Additionally, deadline-expired entries are
PURGED at submit time whenever a cap would shed: a queue full of
already-abandoned work must never pin its table at the cap and shed
live traffic.
"""
from __future__ import annotations

import concurrent.futures
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# fair-share default queue for table-less submits (unit tests, internal
# work): behaves exactly like any other table queue
DEFAULT_QUEUE = ""


class SchedulerSaturatedError(RuntimeError):
    """Raised on submit when the global bound or the submitting table's
    fair-share cap is hit (shed).  Broker-side this is a RETRYABLE
    failure: another replica may have capacity right now."""


class SchedulerShutdownError(RuntimeError):
    """Raised on submit after shutdown.  Broker-side this is RETRYABLE:
    the server is draining for restart, its replicas are not."""


class QueryAbandonedError(RuntimeError):
    """Raised when a queued query's deadline expired before a worker
    picked it up — the broker already gave up on this reply."""


class _Entry:
    __slots__ = ("fn", "future", "deadline", "table", "t_submit")

    def __init__(self, fn, future, deadline, table, t_submit) -> None:
        self.fn = fn
        self.future = future
        self.deadline = deadline
        self.table = table
        self.t_submit = t_submit


# live worker-thread registry for the conftest leak guard (same pattern
# as engine/dispatch.py lane threads): shutdown schedulers must not
# strand workers
_worker_threads: List[threading.Thread] = []
_worker_threads_lock = threading.Lock()


def leaked_scheduler_threads(grace_s: float = 2.0) -> List[threading.Thread]:
    """Worker threads of SHUT-DOWN schedulers still alive after a grace
    period (running schedulers' workers are exempt)."""
    deadline = time.monotonic() + grace_s
    while True:
        with _worker_threads_lock:
            leaked = [
                t
                for t in _worker_threads
                if t.is_alive() and getattr(t, "_sched_shutdown", lambda: False)()
            ]
            _worker_threads[:] = [t for t in _worker_threads if t.is_alive()]
        if not leaked or time.monotonic() >= deadline:
            return leaked
        time.sleep(0.05)


class QueryScheduler:
    def __init__(
        self,
        num_workers: int = 4,
        max_pending: int = 64,
        metrics=None,
        weights: Optional[Dict[str, float]] = None,
    ) -> None:
        self._max_pending = max_pending
        self._num_workers = num_workers
        # per-table FCFS queues + DRR state (all under _cv's lock)
        self._queues: Dict[str, deque] = {}
        self._rr: deque = deque()  # active (non-empty) tables, DRR order
        self._deficit: Dict[str, float] = {}
        self._weights: Dict[str, float] = dict(weights or {})
        # pending = queued + running, maintained by future done-callbacks
        self._pending_total = 0
        self._table_pending: Dict[str, int] = {}
        self._queued_total = 0  # entries sitting in queues (worker wakeup)
        self._running = 0  # workers currently executing an entry
        self._shed = 0
        self._table_shed: Dict[str, int] = {}
        self._abandoned = 0
        self._shutdown = False
        # Condition() uses an RLock: done-callbacks fired while this
        # thread holds the lock (purge/shutdown cancels) re-enter safely
        self._cv = threading.Condition()
        # optional ServerMetrics: pending-depth gauge + the
        # ServerQueryPhase-style queue-wait timer (phase.schedulerWait)
        self.metrics = metrics
        if metrics is not None:
            metrics.gauge("fairshare.activeTables").set_fn(
                lambda: len(self._rr)
            )
            metrics.meter("fairshare.shed")
        self._workers: List[threading.Thread] = []
        for i in range(num_workers):
            t = threading.Thread(
                target=self._worker, name=f"sched-worker-{i}", daemon=True
            )
            t._sched_shutdown = lambda: self._shutdown  # leak-guard hook
            t.start()
            self._workers.append(t)
        with _worker_threads_lock:
            _worker_threads.extend(self._workers)

    # -- weights -------------------------------------------------------
    def set_weight(self, table: str, weight: float) -> None:
        """Fair-share weight for a table (default 1.0, clamped > 0)."""
        with self._cv:
            self._weights[table] = max(float(weight), 0.01)

    def _weight(self, table: str) -> float:
        return max(self._weights.get(table, 1.0), 0.01)

    # -- bookkeeping ---------------------------------------------------
    def _note_pending_locked(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("scheduler.pending").set(self._pending_total)

    @property
    def pending(self) -> int:
        return self._pending_total

    @property
    def max_pending(self) -> int:
        return self._max_pending

    def pending_of(self, table: str) -> int:
        with self._cv:
            return self._table_pending.get(table, 0)

    @property
    def shed_count(self) -> int:
        return self._shed

    @property
    def abandoned_count(self) -> int:
        return self._abandoned

    def stats(self) -> dict:
        """Status-surface snapshot (ServerInstance.status)."""
        with self._cv:
            return {
                "pending": self._pending_total,
                "maxPending": self._max_pending,
                "shed": self._shed,
                "abandoned": self._abandoned,
                "shutdown": self._shutdown,
                "tablePending": {
                    t: n for t, n in sorted(self._table_pending.items()) if n
                },
                "tableShed": dict(sorted(self._table_shed.items())),
                "weights": dict(sorted(self._weights.items())),
            }

    # -- fair-share admission ------------------------------------------
    def _table_cap_locked(self, table: str) -> int:
        """Pending cap for ``table`` right now: the full ``max_pending``
        while it is alone (work-conserving — idle capacity is usable),
        its weighted share of ``max_pending`` once any OTHER table holds
        pending work."""
        others = self._pending_total - self._table_pending.get(table, 0)
        if others <= 0:
            return self._max_pending
        active = {t for t, n in self._table_pending.items() if n > 0}
        active.add(table)
        w = self._weight(table)
        total_w = sum(self._weight(t) for t in active)
        return max(1, int(self._max_pending * w / total_w))

    def _purge_expired_locked(self, now: Optional[float] = None) -> int:
        """Complete deadline-expired QUEUED entries with the typed
        abandon error and free their slots — expired work must never pin
        a queue at its cap.  Returns entries purged."""
        now = time.monotonic() if now is None else now
        purged = 0
        for q in self._queues.values():
            keep = deque()
            while q:
                entry = q.popleft()
                if entry.deadline is not None and now >= entry.deadline:
                    self._queued_total -= 1
                    if entry.future.set_running_or_notify_cancel():
                        self._abandoned += 1
                        entry.future.set_exception(
                            QueryAbandonedError(
                                "deadline expired while queued; broker "
                                "already gave up"
                            )
                        )
                    purged += 1
                elif entry.future.cancelled():
                    self._queued_total -= 1
                    purged += 1
                else:
                    keep.append(entry)
            q.extend(keep)
        return purged

    def _shed_locked(self, table: str, msg: str) -> None:
        self._shed += 1
        self._table_shed[table] = self._table_shed.get(table, 0) + 1
        if self.metrics is not None:
            self.metrics.meter("fairshare.shed").mark()
        raise SchedulerSaturatedError(msg)

    def submit(
        self,
        fn: Callable[[], Any],
        table: str = DEFAULT_QUEUE,
        deadline: Optional[float] = None,
    ) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._cv:
            if self._shutdown:
                raise SchedulerShutdownError("scheduler is shut down")
            if self._pending_total >= self._max_pending:
                # before shedding, reclaim slots pinned by expired work
                self._purge_expired_locked()
            if self._pending_total >= self._max_pending:
                self._shed_locked(
                    table,
                    f"scheduler saturated: {self._pending_total} pending >= "
                    f"{self._max_pending} cap",
                )
            cap = self._table_cap_locked(table)
            if self._table_pending.get(table, 0) >= cap:
                self._purge_expired_locked()
                cap = self._table_cap_locked(table)
            if self._table_pending.get(table, 0) >= cap:
                self._shed_locked(
                    table,
                    f"scheduler saturated for table {table or '<default>'}: "
                    f"{self._table_pending.get(table, 0)} pending >= "
                    f"fair-share cap {cap} "
                    f"({self._pending_total}/{self._max_pending} total)",
                )
            entry = _Entry(fn, fut, deadline, table, time.monotonic())
            q = self._queues.get(table)
            if q is None:
                q = self._queues[table] = deque()
            if not q and table not in self._rr:
                self._rr.append(table)
                self._deficit.setdefault(table, 0.0)
            q.append(entry)
            self._queued_total += 1
            self._pending_total += 1
            self._table_pending[table] = self._table_pending.get(table, 0) + 1
            self._note_pending_locked()
            self._cv.notify()

        def _done(_f) -> None:
            with self._cv:
                self._pending_total -= 1
                n = self._table_pending.get(table, 0) - 1
                if n > 0:
                    self._table_pending[table] = n
                else:
                    self._table_pending.pop(table, None)
                self._note_pending_locked()
                # a freed slot may unblock a worker waiting for work
                # (cancel of a queued twin) — cheap, so always notify
                self._cv.notify()

        fut.add_done_callback(_done)
        return fut

    # -- DRR dequeue ---------------------------------------------------
    def _next_entry_locked(self) -> Optional[_Entry]:
        """One deficit-round-robin pick over the active tables; None if
        every queue is empty.  Unit cost per query: a table earns its
        weight in credit each cycle and spends 1 per dequeue, so over
        any window tables drain proportionally to weight."""
        while self._rr:
            table = self._rr[0]
            q = self._queues.get(table)
            if not q:
                self._rr.popleft()
                self._deficit.pop(table, None)
                continue
            if self._deficit.get(table, 0.0) < 1.0:
                self._deficit[table] = (
                    self._deficit.get(table, 0.0) + self._weight(table)
                )
                self._rr.rotate(-1)
                continue
            self._deficit[table] -= 1.0
            entry = q.popleft()
            self._queued_total -= 1
            if not q:
                # queue drained: leave DRR (deficit resets — classic DRR
                # forgets credit when a flow goes idle)
                if self._rr and self._rr[0] == table:
                    self._rr.popleft()
                else:
                    try:
                        self._rr.remove(table)
                    except ValueError:
                        pass
                self._deficit.pop(table, None)
            return entry
        return None

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._shutdown and self._queued_total == 0:
                    self._cv.wait()
                if self._shutdown and self._queued_total == 0:
                    return
                entry = self._next_entry_locked()
                if entry is None:
                    continue
                self._running += 1
            try:
                self._run_entry(entry)
            finally:
                with self._cv:
                    self._running -= 1

    def _run_entry(self, entry: _Entry) -> None:
        fut = entry.future
        if not fut.set_running_or_notify_cancel():
            return  # cancelled while queued; done-callback freed the slot
        now = time.monotonic()
        if self.metrics is not None:
            # FCFS queue wait — the ServerQueryPhase SCHEDULER_WAIT
            # analog, measured submit -> worker dequeue
            self.metrics.timer("phase.schedulerWait").update(
                (now - entry.t_submit) * 1000.0
            )
        if entry.deadline is not None and now >= entry.deadline:
            with self._cv:
                self._abandoned += 1
            fut.set_exception(
                QueryAbandonedError(
                    "deadline expired while queued; broker already gave up"
                )
            )
            return
        try:
            result = entry.fn()
        except BaseException as e:
            fut.set_exception(e)
        else:
            fut.set_result(result)

    def run(
        self,
        fn: Callable[[], Any],
        timeout_s: float,
        deadline: Optional[float] = None,
        table: str = DEFAULT_QUEUE,
    ) -> Any:
        """Run ``fn`` with at most ``timeout_s`` of wall budget on
        ``table``'s fair-share queue.

        ``deadline`` (monotonic seconds) defaults to now+timeout_s; it is
        checked at dequeue time so a query whose budget drained in the
        queue is shed instead of executed (the broker that sent it has
        already failed over or timed out).
        """
        if deadline is None:
            deadline = time.monotonic() + timeout_s
        if time.monotonic() >= deadline:
            # already expired at submit: abandon without queueing (the
            # dequeue-time check would reach the same verdict later, at
            # the cost of a queue slot meanwhile)
            with self._cv:
                self._abandoned += 1
            raise QueryAbandonedError(
                "deadline expired while queued; broker already gave up"
            )
        fut = self.submit(fn, table=table, deadline=deadline)
        try:
            return fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except concurrent.futures.TimeoutError as e:
            # the client is gone: a still-QUEUED query cancels (its
            # done-callback frees the pending slot immediately) so
            # abandoned work cannot pin the scheduler at max_pending
            # and shed live traffic; a RUNNING one must drain.
            # Re-raised as the builtin TimeoutError (on 3.11+ they are
            # the same class; on 3.10 the futures one is distinct).
            fut.cancel()
            raise TimeoutError(str(e) or "query timed out") from e

    def shutdown(self) -> None:
        """Idempotent: the first call cancels every queued entry across
        ALL per-table queues and stops accepting submits; later calls
        are no-ops.  Running queries drain; workers then exit."""
        with self._cv:
            if self._shutdown:
                return
            self._shutdown = True
            # entries a currently-free worker is about to pick up keep
            # their slot (matches the old pool's cancel_futures contract:
            # work already claimed by a worker still runs); everything
            # beyond that cancels — tail-first so queue heads survive
            keep = min(
                self._queued_total, max(0, self._num_workers - self._running)
            )
            to_cancel = self._queued_total - keep
            while to_cancel > 0:
                table = max(
                    (t for t, q in self._queues.items() if q),
                    key=lambda t: len(self._queues[t]),
                    default=None,
                )
                if table is None:
                    break
                entry = self._queues[table].pop()
                self._queued_total -= 1
                entry.future.cancel()
                to_cancel -= 1
            self._cv.notify_all()
