"""Query scheduler: bounded FCFS pool in front of the executor.

The reference bounds query concurrency with runner/worker pools
(``QueryScheduler.java:35``, ``FCFSQueryScheduler``).  Device execution
is serialized per chip anyway, so the pool here mainly bounds host-side
planning/finalize concurrency and provides the submit/timeout surface.
"""
from __future__ import annotations

import concurrent.futures
from typing import Any, Callable


class QueryScheduler:
    def __init__(self, num_workers: int = 4) -> None:
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=num_workers)

    def submit(self, fn: Callable[[], Any]) -> concurrent.futures.Future:
        return self._pool.submit(fn)

    def run(self, fn: Callable[[], Any], timeout_s: float) -> Any:
        return self.submit(fn).result(timeout=timeout_s)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
