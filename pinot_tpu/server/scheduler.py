"""Query scheduler: bounded FCFS pool + bounded pending queue in front
of the executor.

The reference bounds query concurrency with runner/worker pools
(``QueryScheduler.java:35``, ``FCFSQueryScheduler``); queries beyond
pool capacity wait FCFS, and the serving bar is what happens at
saturation.  Device execution is serialized per chip anyway, so the
pool here bounds the host-side PREP/FINALIZE stages of the serving
pipeline (kernel launches live on the single device lane,
``engine/dispatch.py``) and provides the submit/timeout surface.  The OVERLOAD POLICY (r5): at most
``max_pending`` queries may be queued-or-running; beyond that submits
are shed immediately with ``SchedulerSaturatedError`` rather than
queued without bound — a fast 210-coded error reply beats a timeout
that arrives after the client gave up, and bounds server memory under
a flood (the reference's analog is its scheduler resource limits).

DEADLINE PROPAGATION: the broker serializes its *remaining* budget into
each (re-)issued InstanceRequest, and ``run`` pins that budget as a
monotonic deadline checked when a worker dequeues the query — a query
that waited out its whole budget in the FCFS queue is abandoned
broker-side already, so executing it would only steal capacity from
queries that can still make their deadline.  Such work is shed with
``QueryAbandonedError`` before touching the executor.
"""
from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Any, Callable, Optional


class SchedulerSaturatedError(RuntimeError):
    """Raised on submit when the pending queue is at capacity (shed).
    Broker-side this is a RETRYABLE failure: another replica may have
    capacity right now."""


class SchedulerShutdownError(RuntimeError):
    """Raised on submit after shutdown.  Broker-side this is RETRYABLE:
    the server is draining for restart, its replicas are not."""


class QueryAbandonedError(RuntimeError):
    """Raised when a queued query's deadline expired before a worker
    picked it up — the broker already gave up on this reply."""


class QueryScheduler:
    def __init__(
        self, num_workers: int = 4, max_pending: int = 64, metrics=None
    ) -> None:
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=num_workers)
        self._max_pending = max_pending
        self._pending = 0  # queued + running
        self._shed = 0
        self._abandoned = 0
        self._shutdown = False
        self._lock = threading.Lock()
        # optional ServerMetrics: pending-depth gauge + the
        # ServerQueryPhase-style queue-wait timer (phase.schedulerWait)
        self.metrics = metrics

    def _note_pending_locked(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("scheduler.pending").set(self._pending)

    @property
    def pending(self) -> int:
        return self._pending

    @property
    def shed_count(self) -> int:
        return self._shed

    @property
    def abandoned_count(self) -> int:
        return self._abandoned

    def stats(self) -> dict:
        """Status-surface snapshot (ServerInstance.status)."""
        with self._lock:
            return {
                "pending": self._pending,
                "maxPending": self._max_pending,
                "shed": self._shed,
                "abandoned": self._abandoned,
                "shutdown": self._shutdown,
            }

    def submit(self, fn: Callable[[], Any]) -> concurrent.futures.Future:
        with self._lock:
            if self._shutdown:
                raise SchedulerShutdownError("scheduler is shut down")
            if self._pending >= self._max_pending:
                self._shed += 1
                raise SchedulerSaturatedError(
                    f"scheduler saturated: {self._pending} pending >= "
                    f"{self._max_pending} cap"
                )
            self._pending += 1
            self._note_pending_locked()
        try:
            fut = self._pool.submit(fn)
        except RuntimeError as e:
            # pool shut down between our check and the submit
            with self._lock:
                self._pending -= 1
                self._note_pending_locked()
            raise SchedulerShutdownError(str(e)) from e
        except BaseException:
            with self._lock:
                self._pending -= 1
                self._note_pending_locked()
            raise

        def _done(_f) -> None:
            with self._lock:
                self._pending -= 1
                self._note_pending_locked()

        fut.add_done_callback(_done)
        return fut

    def run(
        self,
        fn: Callable[[], Any],
        timeout_s: float,
        deadline: Optional[float] = None,
    ) -> Any:
        """Run ``fn`` with at most ``timeout_s`` of wall budget.

        ``deadline`` (monotonic seconds) defaults to now+timeout_s; it is
        checked at dequeue time so a query whose budget drained in the
        FCFS queue is shed instead of executed (the broker that sent it
        has already failed over or timed out).
        """
        if deadline is None:
            deadline = time.monotonic() + timeout_s
        t_submit = time.monotonic()

        def _guarded() -> Any:
            now = time.monotonic()
            if self.metrics is not None:
                # FCFS queue wait — the ServerQueryPhase SCHEDULER_WAIT
                # analog, measured submit -> worker dequeue
                self.metrics.timer("phase.schedulerWait").update(
                    (now - t_submit) * 1000.0
                )
            if now >= deadline:
                with self._lock:
                    self._abandoned += 1
                raise QueryAbandonedError(
                    "deadline expired while queued; broker already gave up"
                )
            return fn()

        fut = self.submit(_guarded)
        try:
            return fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except concurrent.futures.TimeoutError as e:
            # the client is gone: a still-QUEUED query cancels (its
            # done-callback frees the pending slot immediately) so
            # abandoned work cannot pin the scheduler at max_pending
            # and shed live traffic; a RUNNING one must drain.
            # Re-raised as the builtin TimeoutError (on 3.11+ they are
            # the same class; on 3.10 the futures one is distinct).
            fut.cancel()
            raise TimeoutError(str(e) or "query timed out") from e

    def shutdown(self) -> None:
        """Idempotent: the first call cancels queued futures and stops
        accepting submits; later calls are no-ops."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._pool.shutdown(wait=False, cancel_futures=True)
