"""Server instance: request handling front for one query-serving node.

The reference chain (``ScheduledRequestHandler.java:55``): Netty bytes
-> Thrift InstanceRequest -> QueryScheduler -> QueryExecutor ->
serialized DataTable bytes.  Here: framed bytes -> InstanceRequest ->
scheduler -> TPU QueryExecutor -> DataTable bytes.  Errors come back as
a DataTable whose ``exceptions`` metadata is set (the broker still
reduces the healthy servers' partials —
``BrokerRequestHandler.java:443-460`` semantics).
"""
from __future__ import annotations

import concurrent.futures
import logging
import os
import time
from typing import List, Optional, Sequence, Tuple

from pinot_tpu.common.datatable import (
    deserialize_instance_request,
    serialize_result,
)
from pinot_tpu.common.response import ErrorCode
from pinot_tpu.engine.executor import QueryExecutor
from pinot_tpu.engine.results import SEGMENT_TIER_KEYS, IntermediateResult
from pinot_tpu.pql import optimize_request, parse_pql
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.server.datamanager import InstanceDataManager
from pinot_tpu.server.scheduler import (
    QueryAbandonedError,
    QueryScheduler,
    SchedulerSaturatedError,
    SchedulerShutdownError,
)
from pinot_tpu.utils.metrics import ServerMetrics, prometheus_text
from pinot_tpu.utils.trace import (
    NULL_TRACE,
    TraceContext,
    reset_current,
    set_current,
)

logger = logging.getLogger(__name__)


class _RooflineWindow:
    """Rolling window of device-served query records backing the
    server-wide ``device.util.achieved*`` gauges: recent achieved
    HBM bytes/s and FLOP/s over the trailing ``window_s`` seconds,
    plus the roofline fraction against the declared platform peaks.
    Records happen on the request path (host side — the lane's
    zero-alloc contract is about the launch path, not here)."""

    def __init__(
        self, window_s: float = 300.0, capacity: int = 2048, peak_scale: int = 1
    ) -> None:
        import collections
        import threading

        self.window_s = window_s
        # how many chips this window's records aggregate over: the
        # roofline denominator scales with it (a lane driving 8 chips
        # measured against ONE chip's peak would overstate up to 8x)
        self.peak_scale = max(1, int(peak_scale))
        self._dq = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._cached: Optional[tuple] = None  # (monotonic t, snapshot)

    def record(self, device_ms: float, device_bytes: float, flops: float) -> None:
        with self._lock:
            self._dq.append(
                (time.monotonic(), float(device_ms), float(device_bytes), float(flops))
            )
            self._cached = None

    def snapshot(self, since: Optional[float] = None) -> dict:
        """``since`` (a ``time.monotonic()`` stamp) narrows the window to
        records at/after that instant — bench uses it to exclude warmup
        (cold-compile) queries from the measured-ladder figures.  The
        0.5s read cache only serves the default full-window view."""
        now = time.monotonic()
        with self._lock:
            if since is None and self._cached is not None and now - self._cached[0] < 0.5:
                return dict(self._cached[1])
            horizon = now - self.window_s
            while self._dq and self._dq[0][0] < horizon:
                self._dq.popleft()
            records = (
                list(self._dq)
                if since is None
                else [r for r in self._dq if r[0] >= since]
            )
            ms = sum(r[1] for r in records)
            nbytes = sum(r[2] for r in records)
            flops = sum(r[3] for r in records)
            n = len(records)
        out = {
            "windowS": self.window_s,
            "queries": n,
            "deviceMs": round(ms, 3),
            "deviceBytes": int(nbytes),
            "achievedBytesPerSec": round(nbytes * 1000.0 / ms, 3) if ms > 0 else 0.0,
            "achievedFlopsPerSec": round(flops * 1000.0 / ms, 3) if ms > 0 else 0.0,
        }
        from pinot_tpu.utils.platform import platform_peaks, roofline_fractions

        peaks = dict(platform_peaks())
        if self.peak_scale != 1:
            for k in ("peakFlopsPerSec", "peakBytesPerSec"):
                if peaks.get(k):
                    peaks[k] = peaks[k] * self.peak_scale
        out["rooflineFraction"] = roofline_fractions(
            out["achievedBytesPerSec"], out["achievedFlopsPerSec"], peaks=peaks
        )["rooflineFraction"]
        if since is None:
            with self._lock:
                self._cached = (now, dict(out))
        return out


class ServerInstance:
    def __init__(
        self,
        name: str = "server0",
        mesh=None,
        num_workers: int = 4,
        max_pending: int = 64,
        pipeline: Optional[bool] = None,
        lane_stall_timeout_s: Optional[float] = None,
        device_fault_injector=None,
        topology=None,
    ) -> None:
        self.name = name
        self.data_manager = InstanceDataManager()
        self.metrics = ServerMetrics(name)
        # mesh execution plane (engine/mesh.py): the server's chips
        # carve into chip groups — one DeviceLane per group, queries
        # shape-hash-routed across them, each group executing as one
        # SPMD program over its own mesh.  ``topology`` wins; a legacy
        # ``mesh`` argument becomes a one-lane topology driving that
        # mesh; with neither, the env (PINOT_TPU_MESH_SHAPE /
        # PINOT_TPU_LANES) decides — unset env is the trivial single
        # lane, the exact pre-mesh path (and touches no jax state).
        from pinot_tpu.engine.mesh import MeshTopology

        if topology is None:
            topology = (
                MeshTopology.from_mesh(mesh)
                if mesh is not None
                else MeshTopology.from_env()
            )
        self.topology = topology
        self.metrics.gauge("mesh.lanes").set(topology.num_lanes)
        self.metrics.gauge("mesh.devices").set(topology.num_devices)
        self.metrics.gauge("mesh.devicesPerLane").set(topology.devices_per_lane)
        # three-stage serving pipeline (engine/dispatch.py): PREP on the
        # scheduler's worker pool, kernel launches on the per-chip-group
        # device lanes (coalescing identical dispatches), FINALIZE back
        # on the submitting worker.  On by default; PINOT_TPU_PIPELINE=0
        # (or pipeline=False) restores the serial per-worker path.
        # ``lane_stall_timeout_s`` arms the lane watchdogs (wedged-launch
        # restart); ``device_fault_injector`` is the deterministic-chaos
        # hook (common/faults.py DeviceFaultInjector), consulted by
        # every lane.
        if pipeline is None:
            pipeline = os.environ.get("PINOT_TPU_PIPELINE", "1") != "0"
        from pinot_tpu.engine.dispatch import LaneGroup

        self.lanes = (
            LaneGroup(
                topology,
                metrics=self.metrics,
                stall_timeout_s=lane_stall_timeout_s,
                fault_injector=device_fault_injector,
            )
            if pipeline
            else None
        )
        # back-compat handle: the primary lane (THE lane on single-lane
        # servers — the overwhelmingly common configuration)
        self.lane = self.lanes.primary if self.lanes is not None else None
        self.executor = QueryExecutor(
            mesh=topology.primary_mesh if self.lanes is None else None,
            metrics=self.metrics,
            lane=self.lane,
            lanes=self.lanes,
        )
        self.scheduler = QueryScheduler(
            num_workers=num_workers, max_pending=max_pending, metrics=self.metrics
        )
        # pre-register the serving/integrity series (zero > absent on a
        # scrape); lane.* and heal.* register in their constructors
        for m in ("queries", "queriesShed", "queriesAbandoned",
                  "segmentsMissedServing", "crcFailures", "quarantinedSegments"):
            self.metrics.meter(m)
        # cost-accounting plane (PR 6): per-query cost totals summed
        # into the registry, plus the HBM staging-ledger gauges — the
        # capacity signal admission control / multichip staging consume.
        # All pre-registered so /metrics shows zeros before first use.
        for m in ("cost.docsScanned", "cost.bytesScanned"):
            self.metrics.meter(m)
        for t in ("cost.deviceMs", "cost.hostMs"):
            self.metrics.timer(t)
        for m in ("ingest.rowsConsumed",):
            self.metrics.meter(m)
        self.metrics.timer("ingest.commitMs")
        # distributed-join plane (engine/join.py): extraction + hash
        # join execution counters, pre-registered
        for m in (
            "join.extracts", "join.execs", "join.buildRows",
            "join.probeRows", "join.shuffleBytes", "join.broadcastBytes",
        ):
            self.metrics.meter(m)
        # workload-introspection plane: per-plan-digest rolling stats
        # (utils/planstats.py) behind /debug/plans + status()["plans"],
        # with the plan.* series and the per-tier cost counters the
        # /debug/plans tier mixes reconcile against — all pre-registered
        from pinot_tpu.utils.planstats import PlanStatsStore

        self.plan_stats = PlanStatsStore()
        for m in ("plan.recorded", "plan.explains"):
            self.metrics.meter(m)
        self.metrics.gauge("plan.digests").set_fn(self.plan_stats.digest_count)
        # ingest-aware result cache (engine/rescache.py, opt-in via
        # PINOT_TPU_RESULT_CACHE=1): keyed on (plan shape digest,
        # literal digest, segment set + staging tokens) so a stale
        # realtime answer is structurally unreachable, and invalidated
        # eagerly by LLC offset advancement + segment set changes
        from pinot_tpu.engine.rescache import ResultCache

        self.result_cache = ResultCache(metrics=self.metrics)
        for k in self._TIER_KEYS:
            self.metrics.meter(f"cost.tier.{k}")
        # device utilization & profiling plane (PR 10): occupancy +
        # achieved-rate gauges, H2D/D2H transfer counters, and the
        # on-demand jax.profiler bracket.  All pre-registered; the
        # occupancy gauges are windowed lane reads (0 while idle), the
        # sampler is opt-in (zero per-launch overhead until started).
        from pinot_tpu.engine.device import TRANSFERS
        from pinot_tpu.engine.dispatch import OccupancySampler
        from pinot_tpu.server.profiler import DeviceProfiler

        # one roofline window per lane (chip group): /debug/device and
        # the fleet rollup attribute achieved rates per lane, with the
        # rollup computed FROM the per-lane snapshots so totals always
        # equal the sum of lane snapshots.  Single-lane servers see the
        # pre-mesh single-window shape verbatim.
        if self.lanes is not None and self.lanes.size > 1:
            # per-lane windows measure against the lane's OWN chip
            # count; the rollup then divides by the full device count
            scales = [g.size for g in topology.groups]
        else:
            # one window covering every chip this server drives (1 on
            # the trivial topology — the pre-mesh figures unchanged)
            scales = [max(1, topology.num_devices)]
        self._roofline_windows = [_RooflineWindow(peak_scale=s) for s in scales]
        self._roofline_window = self._roofline_windows[0]
        self.profiler = DeviceProfiler(name=name, metrics=self.metrics)
        # one occupancy sampler per lane: a profiler bracket on a
        # lane-group server must trace EVERY chip group's occupancy,
        # not just lane 0's
        self.occupancy_samplers = (
            [OccupancySampler(lane) for lane in self.lanes.lanes]
            if self.lanes is not None
            else []
        )
        self.occupancy_sampler = (
            self.occupancy_samplers[0] if self.occupancy_samplers else None
        )
        if self.occupancy_samplers:
            # a deep-profile bracket records the occupancy time series
            # (every lane's) alongside the XLA trace; the samplers park
            # again when the capture ends (stop OR auto-stop)
            self.profiler.on_capture_end = self._stop_samplers
        if self.lanes is not None:
            lanes = self.lanes
            self.metrics.gauge("device.util.busyFraction").set_fn(
                lambda: lanes.occupancy_read("gauge", min_interval_s=0.05)[
                    "busyFraction"
                ]
            )
            self.metrics.gauge("device.util.avgQueueDepth").set_fn(
                lambda: lanes.occupancy_read("gauge", min_interval_s=0.05)[
                    "avgQueueDepth"
                ]
            )
        else:
            self.metrics.gauge("device.util.busyFraction").set(0)
            self.metrics.gauge("device.util.avgQueueDepth").set(0)
        self.metrics.gauge("device.util.h2dBytes").set_fn(
            lambda: TRANSFERS.h2d_bytes
        )
        self.metrics.gauge("device.util.d2hBytes").set_fn(
            lambda: TRANSFERS.d2h_bytes
        )
        self.metrics.gauge("device.util.achievedBytesPerSec").set_fn(
            lambda: self._roofline_rollup()["achievedBytesPerSec"]
        )
        self.metrics.gauge("device.util.achievedFlopsPerSec").set_fn(
            lambda: self._roofline_rollup()["achievedFlopsPerSec"]
        )
        self.metrics.gauge("device.util.rooflineFraction").set_fn(
            lambda: self._roofline_rollup()["rooflineFraction"]
        )
        from pinot_tpu.engine.device import LEDGER

        # NOTE: the ledger (like the staging cache) is process-global —
        # one device per process; in-process multi-server harnesses see
        # the same figure on every instance
        self.metrics.gauge("hbm.stagedBytes").set_fn(LEDGER.total_bytes)
        self.metrics.gauge("hbm.highWatermarkBytes").set_fn(
            lambda: LEDGER.high_watermark
        )
        self.metrics.gauge("hbm.stagedTables").set_fn(LEDGER.table_count)
        self.metrics.gauge("hbm.evictedBytes").set_fn(lambda: LEDGER.evicted_bytes)
        self.metrics.gauge("hbm.qinputCacheBytes").set_fn(
            lambda: self.executor._qinput_cache_bytes
        )
        # tiered residency plane (engine/residency.py — process-global,
        # like the ledger): per-tier bytes/counts, cap pressure, and
        # the demotion/promotion cycle counters
        from pinot_tpu.engine.residency import RESIDENCY

        self.metrics.gauge("residency.hotBytes").set_fn(RESIDENCY.hot_bytes)
        self.metrics.gauge("residency.warmBytes").set_fn(RESIDENCY.warm_bytes)
        self.metrics.gauge("residency.coldBytes").set_fn(RESIDENCY.cold_bytes)
        self.metrics.gauge("residency.pressure").set_fn(RESIDENCY.pressure)
        for _rc in (
            "demotions",
            "promotions",
            "coldDemotions",
            "coldLoads",
            "pressureDemotions",
            "prefetches",
        ):
            self.metrics.gauge(f"residency.{_rc}").set_fn(
                (lambda name: lambda: RESIDENCY.counter(name))(_rc)
            )
        for _rt in ("hot", "warm", "cold"):
            self.metrics.gauge(f"residency.{_rt}Tables").set_fn(
                (lambda t: lambda: RESIDENCY.snapshot()[f"{t}Tables"])(_rt)
            )
        # ingest backpressure governor (realtime/backpressure.py):
        # watermark pause/resume against the HBM staging ledger and the
        # instance's consuming-segment memory, shared by every realtime
        # consumer hosted here (in-process llc.py + networked
        # RemoteConsumer).  Watermarks default off; env-configured.
        from pinot_tpu.realtime.backpressure import (
            IngestBackpressure,
            instance_mutable_bytes,
        )

        self.ingest_backpressure = IngestBackpressure(
            metrics=self.metrics,
            mutable_bytes_fn=lambda: instance_mutable_bytes(self),
        )
        self._table_schemas: dict = {}  # raw table name -> Schema
        # controller-acknowledged drain state (set from the heartbeat
        # reply by the networked starter): the instance keeps serving —
        # brokers simply stop routing new covers here — but ops can see
        # the drain in status()/debug output
        self.draining = False
        # serving lease (common/fencing.py): renewed from heartbeat
        # replies by the networked starter.  While expired this server
        # keeps SERVING (read path up) but has no WRITE authority —
        # consumers freeze their completion rounds and new CONSUMING
        # transitions are deferred.  Unleased (in-process, no gateway)
        # means implicit authority.  Registers lease.held/renewals/
        # expiries; the blocked-write counters are pre-registered here.
        from pinot_tpu.common.fencing import ServingLease

        self.lease = ServingLease(metrics=self.metrics)
        for m in ("lease.blockedCommits", "lease.blockedTransitions"):
            self.metrics.meter(m)
        # controller reachability (set by the networked starter's
        # heartbeat loop): 1 while consecutive heartbeats are failing —
        # the "partitioned but riding it out" observable
        self.metrics.gauge("controller.unreachable").set(0)
        self.metrics.meter("controller.heartbeatFailures")
        # SLO & tail-latency attribution plane (ISSUE 11): one history
        # thread snapshots this registry on a cadence (served at
        # /debug/history on the admin surface); heal events spotted on
        # its tick dump a flight-recorder bundle (disabled unless
        # PINOT_TPU_FLIGHTREC_DIR is set)
        from pinot_tpu.utils.flightrec import FlightRecorder
        from pinot_tpu.utils.timeseries import HistoryRecorder

        self.history = HistoryRecorder(self.metrics, metrics=self.metrics)
        self.flightrec = FlightRecorder(
            "server",
            name,
            metrics=self.metrics,
            sources={
                "history": lambda: self.history.query(window_s=900),
                "plans": lambda: self.plan_stats.snapshot(top=20),
                "device": self.device_utilization,
                "status": self.status,
                # lazy: the auditor is constructed a few lines below
                "audit": lambda: self.auditor.snapshot(),
            },
        )
        self._last_heal_total = 0
        self.history.add_tick_hook(self._history_tick)
        # warm-start plane (server/prewarm.py): background compile
        # driver for the fleet's hot plan shapes.  Inert until a starter
        # wires a workload source (and PINOT_TPU_PREWARM_TOP_K > 0);
        # segment loads then trigger passes and status()/heartbeats
        # report the warming/ready flag brokers and the rebalancer
        # consume.
        from pinot_tpu.server.prewarm import PrewarmWorker

        self.prewarm = PrewarmWorker(self)
        # continuous correctness audit (utils/audit.py): background
        # shadow differential sampler re-checking 1-in-N production
        # replies against the host oracle — always on by default
        # (PINOT_TPU_AUDIT_SAMPLE_N=0 disables)
        from pinot_tpu.utils.audit import ShadowAuditor

        self.auditor = ShadowAuditor(self)

    # serving-tier cost-vector keys mirrored into cost.tier.* meters —
    # the ONE source in engine/results.py, so a new tier cannot
    # silently miss the reconciliation surfaces
    _TIER_KEYS = SEGMENT_TIER_KEYS

    def _roofline_rollup(self, since: Optional[float] = None) -> dict:
        """Recent achieved-rate window across every lane.  Single lane:
        the window's snapshot verbatim (pre-mesh shape).  Lane group:
        per-lane snapshots under ``lanes`` plus a rollup computed FROM
        those snapshots — totals and achieved rates are sums over the
        concurrent lanes, and the fleet roofline fraction divides by
        the per-chip peak times the server's device count."""
        if len(self._roofline_windows) == 1:
            return self._roofline_windows[0].snapshot(since=since)
        lanes = [w.snapshot(since=since) for w in self._roofline_windows]
        out = {
            "windowS": lanes[0]["windowS"],
            "queries": sum(l["queries"] for l in lanes),
            "deviceMs": round(sum(l["deviceMs"] for l in lanes), 3),
            "deviceBytes": sum(l["deviceBytes"] for l in lanes),
            "achievedBytesPerSec": sum(l["achievedBytesPerSec"] for l in lanes),
            "achievedFlopsPerSec": sum(l["achievedFlopsPerSec"] for l in lanes),
            "lanes": lanes,
        }
        from pinot_tpu.utils.platform import platform_peaks, roofline_fractions

        peaks = dict(platform_peaks())
        n_dev = max(1, self.topology.num_devices)
        for k in ("peakFlopsPerSec", "peakBytesPerSec"):
            if peaks.get(k):
                peaks[k] = peaks[k] * n_dev
        out["rooflineFraction"] = roofline_fractions(
            out["achievedBytesPerSec"], out["achievedFlopsPerSec"], peaks=peaks
        )["rooflineFraction"]
        return out

    # -- segment lifecycle -------------------------------------------
    @staticmethod
    def _raw_table(table: str) -> str:
        for suffix in ("_OFFLINE", "_REALTIME"):
            if table.endswith(suffix):
                return table[: -len(suffix)]
        return table

    def set_table_schema(self, table: str, schema) -> None:
        """Register (or evolve) the table schema.  Existing segments are
        patched with default columns for any schema-added fields, so old
        rows keep answering after schema growth instead of being pruned
        (reference: SegmentPreProcessor -> BaseDefaultColumnHandler)."""
        from pinot_tpu.segment.default_column import inject_default_columns

        raw = self._raw_table(table)
        if self._table_schemas.get(raw) == schema:
            return  # unchanged: skip the retro-patch loop (reload CRC-skip path)
        self._table_schemas[raw] = schema
        for tname in self.data_manager.table_names():
            if self._raw_table(tname) != raw:
                continue
            tdm = self.data_manager.table(tname)
            acquired = tdm.acquire_segments()
            try:
                for sdm in acquired:
                    # only sealed segments: a consuming MutableSegment's
                    # query_view() is a throwaway snapshot rebuilt from
                    # its own schema on the next row batch — patching it
                    # would silently un-patch; it keeps being pruned for
                    # queries on the new column until it seals (the
                    # reference likewise applies schema changes to
                    # consuming segments only at the next rollover)
                    if isinstance(sdm.segment, ImmutableSegment):
                        inject_default_columns(sdm.segment, schema)
            finally:
                tdm.release_segments(acquired)

    def add_segment(
        self, table: str, segment: ImmutableSegment, verify_crc: bool = False
    ) -> None:
        """``verify_crc=True`` (the disk-load paths) recomputes the
        column-data CRC against the metadata claim before the segment
        can serve; a mismatch raises ``SegmentIntegrityError`` and
        counts a ``crcFailures`` mark (the caller quarantines)."""
        if verify_crc:
            # BEFORE default-column injection: injected columns are not
            # part of the on-disk CRC claim and would skew the recompute
            from pinot_tpu.segment.format import verify_segment_crc

            try:
                verify_segment_crc(segment)
            except Exception:
                self.metrics.meter("crcFailures").mark()
                raise
        schema = self._table_schemas.get(self._raw_table(table))
        if schema is not None and isinstance(segment, ImmutableSegment):
            from pinot_tpu.segment.default_column import inject_default_columns

            inject_default_columns(segment, schema)
        self.data_manager.add_segment(table, segment)
        # segment set changed: cached answers over the old cover are
        # superseded (the staleness fence's segment-lifecycle edge)
        self.result_cache.invalidate_table(self._raw_table(table))
        # and the compile working set may have grown: kick a prewarm
        # pass (debounced; inert without a wired workload source)
        self.prewarm.request_prewarm(self._raw_table(table))

    def remove_segment(self, table: str, name: str) -> None:
        tdm = self.data_manager.table(table)
        if tdm is not None:
            tdm.remove_segment(name)
        self.result_cache.invalidate_table(self._raw_table(table))

    def record_crc_failure(self, table: str, name: str) -> None:
        """A disk copy failed its integrity check (load or fetch)."""
        logger.warning("segment %s/%s failed CRC verification", table, name)
        self.metrics.meter("crcFailures").mark()

    def quarantine_segment(self, table: str, name: str) -> None:
        """Pull a corrupt segment out of serving: drop it from the data
        manager AND evict any staged device arrays built from the
        corrupt load — the staging cache keys on (name, claimed crc),
        which a clean re-fetch would collide with."""
        from pinot_tpu.engine.device import evict_staged_segment

        self.remove_segment(table, name)
        evict_staged_segment(name)
        self.metrics.meter("quarantinedSegments").mark()
        logger.warning("segment %s/%s quarantined pending re-fetch", table, name)

    # -- query path ---------------------------------------------------
    def handle_request(self, payload: bytes) -> bytes:
        """Framed request bytes -> framed DataTable bytes."""
        t_start = time.perf_counter()
        req = deserialize_instance_request(payload)
        # ONE deadline for both queueing tiers: the scheduler checks it
        # at worker-dequeue time, the device lane at launch-dequeue time
        timeout_s = req["timeoutMs"] / 1000.0
        deadline = time.monotonic() + timeout_s
        t_enqueue = time.monotonic()
        outcome = "ok"  # vs "shed" / "failed": the plan-stats verdict
        try:
            # fair-share scheduling: each table queues separately and the
            # DRR dequeue guarantees a flooding tenant cannot starve the
            # others (server/scheduler.py)
            result = self.scheduler.run(
                lambda: self._process(req, deadline, t_enqueue),
                timeout_s=timeout_s,
                deadline=deadline,
                table=req["table"],
            )
        except SchedulerSaturatedError as e:
            # overload shed: fast typed rejection, no stack spam — the
            # broker treats 210 as retryable and fails over to a replica
            self.metrics.meter("queriesShed").mark()
            outcome = "shed"
            result = IntermediateResult(
                exceptions=[(ErrorCode.SERVER_SCHEDULER_DOWN, str(e))]
            )
        except SchedulerShutdownError as e:
            # draining for restart: typed 220 so the broker retries the
            # segment set on a replica instead of failing the query
            outcome = "shed"
            result = IntermediateResult(
                exceptions=[(ErrorCode.SERVER_SHUTTING_DOWN, str(e))]
            )
        except QueryAbandonedError as e:
            # the broker-propagated deadline expired while this query sat
            # in the FCFS queue; reply cheaply without executing
            self.metrics.meter("queriesAbandoned").mark()
            outcome = "shed"
            result = IntermediateResult(
                exceptions=[(ErrorCode.EXECUTION_TIMEOUT, f"server {self.name}: {e}")]
            )
        except (concurrent.futures.TimeoutError, TimeoutError):
            logger.warning("query %s timed out", req.get("requestId"))
            outcome = "failed"
            result = IntermediateResult(
                exceptions=[
                    (
                        ErrorCode.EXECUTION_TIMEOUT,
                        f"server {self.name}: exceeded {req['timeoutMs']}ms",
                    )
                ]
            )
        except Exception as e:  # execution error
            logger.exception("query %s failed", req.get("requestId"))
            outcome = "failed"
            result = IntermediateResult(
                exceptions=[(ErrorCode.QUERY_EXECUTION, f"{type(e).__name__}: {e}")]
            )
        # per-query cost totals summed into the registry (the server
        # half of the cost-accounting plane; the broker attributes the
        # merged vector per table) — error results carry zero cost
        self.metrics.meter("cost.docsScanned").mark(int(result.num_docs_scanned))
        self.metrics.meter("cost.bytesScanned").mark(
            int(result.cost.get("bytesScanned", 0))
        )
        for key, timer in (("deviceMs", "cost.deviceMs"), ("hostMs", "cost.hostMs")):
            ms = result.cost.get(key)
            if ms:
                self.metrics.timer(timer).update(float(ms))
        # serving-tier counters: the cost-vector segment counts mirrored
        # into per-tier meters so /debug/plans tier mixes reconcile with
        # a registry-level series (all zero for plain EXPLAIN)
        for key in self._TIER_KEYS:
            n = result.cost.get(key)
            if n:
                self.metrics.meter(f"cost.tier.{key}").mark(int(n))
        exec_ms = (time.perf_counter() - t_start) * 1000
        self._record_plan_stats(req, result, outcome, exec_ms)
        self.metrics.timer("queryExecution").update(exec_ms)
        self.metrics.meter("queries").mark()
        # event-time freshness stamp (broker/freshness.py): realtime
        # tables carry their stalest consumed partition watermark on the
        # reply so the broker can derive freshnessMs; offline tables
        # have no watermark entries and stamp nothing — their payloads
        # stay byte-identical to the pre-audit-plane wire format
        from pinot_tpu.broker.freshness import WATERMARKS

        wm = WATERMARKS.table_min_ms(req["table"])
        if wm is not None:
            result.freshness = {"minEventMs": wm}
        # backpressure snapshot on EVERY reply (including sheds): the
        # broker's AIMD admission window reads it to back off before
        # this server has to shed with 210s
        result.backpressure = {
            "pending": self.scheduler.pending,
            "maxPending": self.scheduler.max_pending,
            "laneDepth": 0
            if self.lanes is None
            else self.lanes.stats().get("depth", 0),
        }
        return serialize_result(result)

    def _record_plan_stats(
        self, req: dict, result: IntermediateResult, outcome: str, exec_ms: float
    ) -> None:
        """Fold one handled request into the per-plan-digest registry.
        Plain EXPLAIN is excluded (it executed nothing and must mark no
        cost).  A result without a digest never got parsed: for SHED
        outcomes that is the overload fast-rejection path — re-parsing
        there would spend CPU exactly when the server is saturated, so
        un-keyed sheds are simply not per-digest-attributed (the
        aggregate queriesShed meter still counts them).  Failed
        outcomes (exceptional by definition) re-derive the digest so
        failures cross-link to their shape."""
        digest = getattr(result, "_plan_digest", None)
        summary = getattr(result, "_plan_summary", "")
        explain_mode = getattr(result, "_explain_mode", None)
        if digest is None:
            if outcome == "shed":
                return  # never parse on the overload fast path
            try:
                from pinot_tpu.engine.plandigest import (
                    plan_shape_digest,
                    plan_shape_summary,
                )

                preq = optimize_request(parse_pql(req["pql"]))
                digest = plan_shape_digest(preq)
                summary = plan_shape_summary(preq)
                explain_mode = preq.explain
            except Exception:
                return  # unparseable request: nothing to key on
        if explain_mode == "plan":
            return
        # utilization join: the device-plan digest (when this query ran
        # on device) links the shape's measured wall time to the lane's
        # static cost analysis — the per-digest roofline numerator
        device_ms = float(result.cost.get("deviceMs", 0) or 0)
        host_ms = float(result.cost.get("hostMs", 0) or 0)
        device_info = None
        ddigest = getattr(result, "_device_digest", None)
        lane_idx = int(getattr(result, "_lane_index", 0) or 0)
        lane_idx = min(lane_idx, len(self._roofline_windows) - 1)
        if ddigest is not None and self.lanes is not None:
            # the executor stamped which chip-group lane executed; that
            # lane's compile registry holds the digest's cost analysis
            lane = self.lanes.lanes[lane_idx]
            ci = lane.compile_info(ddigest)
            if ci is None:
                ci = self.lanes.compile_info(ddigest)
            if ci is not None:
                device_info = {"digest": ddigest}
                if self.lanes.size > 1:
                    device_info["lane"] = lane_idx
                analysis = ci.get("costAnalysis")
                if isinstance(analysis, dict):
                    device_info.update(
                        {
                            k: analysis[k]
                            for k in ("flops", "bytesAccessed", "peakMemoryBytes")
                            if k in analysis
                        }
                    )
        if device_ms > 0:
            self._roofline_windows[lane_idx].record(
                device_ms,
                float(result.cost.get("deviceBytes", 0) or 0),
                float((device_info or {}).get("flops", 0) or 0),
            )
        self.plan_stats.record(
            digest,
            summary=summary,
            table=req["table"],
            latency_ms=exec_ms,
            cost=result.cost,
            num_docs=result.num_docs_scanned,
            shed=(outcome == "shed"),
            failed=(outcome == "failed"),
            device_ms=device_ms or None,
            host_ms=host_ms or None,
            device_info=device_info,
        )
        self.metrics.meter("plan.recorded").mark()

    def _history_tick(self, now: float) -> None:
        """Flight-recorder trigger on the history cadence: any heal
        activity since the last sample (device failures healed over to
        host, lane restarts, CRC quarantines) is a notable event whose
        surrounding state is worth keeping."""
        total = (
            self.metrics.meter("heal.deviceFailures").count
            + self.metrics.meter("heal.hostFailovers").count
            + self.metrics.meter("crcFailures").count
            + (0 if self.lanes is None else self.lanes.restart_count)
        )
        delta = total - self._last_heal_total
        self._last_heal_total = total
        if delta > 0:
            self.flightrec.maybe_dump("healEvent", {"healEventsThisTick": delta})

    def status(self) -> dict:
        """Serving-surface snapshot: scheduler depth/shed, device-lane
        depth + coalesce/dispatch/shed counters, the per-stage phase
        timers (staging/planBuild/laneWait/planExec/finalize) inside the
        metrics snapshot, and the self-healing counters (device
        failures, host failovers, lane restarts, poisoned plans, CRC
        failures, quarantined segments)."""
        heal = self.executor.healing_stats()
        heal["laneRestarts"] = 0 if self.lanes is None else self.lanes.restart_count
        heal["crcFailures"] = self.metrics.meter("crcFailures").count
        heal["quarantinedSegments"] = self.metrics.meter("quarantinedSegments").count
        from pinot_tpu.engine.device import LEDGER
        from pinot_tpu.engine.residency import RESIDENCY

        hbm = LEDGER.snapshot()
        hbm["qinputCacheBytes"] = self.executor._qinput_cache_bytes
        return {
            "name": self.name,
            "draining": self.draining,
            "warming": self.prewarm.warming,
            "ready": not self.prewarm.warming,
            "prewarm": self.prewarm.state(),
            "lease": self.lease.snapshot(),
            "scheduler": self.scheduler.stats(),
            # single lane: the lane's stats verbatim; lane group: the
            # summed rollup with a per-lane list under "lanes"
            "lane": None if self.lanes is None else self.lanes.stats(),
            "mesh": self.topology.snapshot(),
            "selfHealing": heal,
            "hbm": hbm,
            "residency": RESIDENCY.snapshot(),
            "device": self.device_utilization(),
            "ingest": self.ingest_backpressure.snapshot(),
            "rescache": self.result_cache.snapshot(),
            "audit": self.auditor.snapshot(),
            "plans": self.plan_stats.snapshot(top=20),
            "metrics": self.metrics.snapshot(),
        }

    def audit_snapshot(self) -> dict:
        """``/debug/audit`` (admin surface): the shadow-audit sampler's
        counters + quarantined (digest, tier) pairs."""
        return self.auditor.snapshot()

    def segment_crcs(self) -> dict:
        """``/debug/segments``: every hosted sealed segment's claimed
        CRC, for the controller's cross-replica checksum sweep
        (``CrcAuditManager``).  Consuming mutable segments carry no CRC
        claim yet and are omitted."""
        out: Dict[str, Dict[str, int]] = {}
        for tname in self.data_manager.table_names():
            tdm = self.data_manager.table(tname)
            if tdm is None:
                continue
            acquired = tdm.acquire_segments()
            try:
                for sdm in acquired:
                    meta = getattr(sdm.segment, "metadata", None)
                    crc = getattr(meta, "crc", None)
                    if crc is not None:
                        out.setdefault(tname, {})[sdm.name] = int(crc)
            finally:
                tdm.release_segments(acquired)
        return {"segments": out}

    def segment_copy_bytes(self, table: str, segment: str) -> Optional[bytes]:
        """Serialize this server's loaded copy of a sealed segment for
        reverse replication (the ``DeepStoreScrubber`` repairing a
        lost/corrupt deep-store copy from a live replica).  The copy is
        CRC-verified BEFORE serialization — a donor must never launder
        its own rot into the durable store.  Returns None when the
        segment isn't hosted here, is still mutable (consuming), or
        fails verification."""
        import tempfile

        from pinot_tpu.segment.format import (
            SEGMENT_FILE_NAME,
            SegmentIntegrityError,
            verify_segment_crc,
            write_segment,
        )

        tdm = self.data_manager.table(table)
        if tdm is None:
            return None
        acquired = tdm.acquire_segments()
        try:
            for sdm in acquired:
                if sdm.name != segment:
                    continue
                seg = sdm.segment
                if getattr(seg, "metadata", None) is None or not hasattr(
                    seg, "columns"
                ):
                    return None  # mutable consuming segment: no durable form
                try:
                    verify_segment_crc(seg, source=f"donor:{self.name}")
                except SegmentIntegrityError:
                    return None
                with tempfile.TemporaryDirectory() as td:
                    write_segment(seg, td)
                    with open(os.path.join(td, SEGMENT_FILE_NAME), "rb") as f:
                        return f.read()
        finally:
            tdm.release_segments(acquired)
        return None

    def profile_start(self, timeout_s: Optional[float] = None) -> dict:
        """Begin (or join) an on-demand profile capture: the jax
        profiler trace starts/extends AND the lane occupancy sampler
        runs for the capture's duration.  Raises
        ``ProfilerUnavailableError`` (typed 404 on the admin surface)
        when the backend has no working profiler."""
        snap = self.profiler.start(timeout_s)
        for sampler in self.occupancy_samplers:
            sampler.start()
        return snap

    def _stop_samplers(self) -> None:
        for sampler in self.occupancy_samplers:
            sampler.stop()

    def profile_stop(self) -> dict:
        """Release one profile start; sampler parks when the capture
        actually ends (refcount zero — the on_capture_end hook)."""
        return self.profiler.stop()

    def device_utilization(self, roofline_since: Optional[float] = None) -> dict:
        """Device utilization snapshot (the ``status()["device"]``
        section and the controller ``/debug/utilization`` rollup's
        per-server unit): declared platform peaks, windowed lane
        occupancy, cumulative H2D/D2H transfer totals, the recent
        achieved-rate window (optionally narrowed to records at/after
        the ``roofline_since`` monotonic stamp), profiler state, and
        (when the opt-in sampler is running) its queue-depth-over-time
        ring."""
        from pinot_tpu.engine.device import TRANSFERS
        from pinot_tpu.utils.platform import platform_peaks

        occupancy = None
        if self.lanes is not None:
            occupancy = self.lanes.occupancy_read("status")
            occupancy["open"] = self.lanes.stats().get("open", 0)
        out = {
            "platform": platform_peaks(),
            "mesh": self.topology.snapshot(),
            "occupancy": occupancy,
            "transfers": TRANSFERS.snapshot(),
            "recent": self._roofline_rollup(since=roofline_since),
            "profiler": self.profiler.snapshot(),
        }
        if self.occupancy_sampler is not None and (
            self.occupancy_sampler.running
            or self.occupancy_sampler.samples_taken
        ):
            out["sampler"] = self.occupancy_sampler.snapshot()
        if len(self.occupancy_samplers) > 1 and any(
            s.running or s.samples_taken for s in self.occupancy_samplers
        ):
            out["samplers"] = [s.snapshot() for s in self.occupancy_samplers]
        return out

    def metrics_text(self) -> str:
        """Prometheus exposition of this server's registry (served at
        ``/metrics`` by the admin HTTP surface).  The lane/scheduler
        gauges update on activity; self-healing counters live in the
        same registry (heal.*, crcFailures, quarantinedSegments)."""
        return prometheus_text(self.metrics)

    def shutdown(self) -> None:
        """Idempotent: drain-stop the scheduler, close the device lane
        (queued lane waiters fail fast with LaneClosedError), stop the
        occupancy sampler, and force-stop any active profile capture."""
        self.scheduler.shutdown()
        self.prewarm.stop()
        self.auditor.stop()
        self.history.stop()
        self._stop_samplers()
        self.profiler.shutdown()
        if self.lanes is not None:
            self.lanes.close()

    def _process(
        self,
        req: dict,
        deadline: Optional[float] = None,
        t_enqueue: Optional[float] = None,
    ) -> IntermediateResult:
        request = parse_pql(req["pql"])
        request.debug_options = dict(req.get("debugOptions") or {})
        request = optimize_request(request)
        request.enable_trace = bool(req.get("trace"))
        # untraced requests share the NULL context: no span allocation
        # anywhere on this path (the zero-overhead contract)
        if request.enable_trace:
            trace = TraceContext(
                enabled=True, scope=self.name, trace_id=str(req.get("requestId") or "")
            )
        else:
            trace = NULL_TRACE
        token = set_current(trace if trace.enabled else None)
        try:
            result = self._process_traced(req, request, trace, deadline, t_enqueue)
        finally:
            reset_current(token)
        # plan-stats keying, computed where the parsed request exists so
        # handle_request's recording path needs no second parse
        from pinot_tpu.engine.plandigest import plan_shape_digest, plan_shape_summary

        result._plan_digest = plan_shape_digest(request)
        result._plan_summary = plan_shape_summary(request)
        result._explain_mode = request.explain
        return result

    def _process_traced(
        self,
        req: dict,
        request,
        trace: TraceContext,
        deadline: Optional[float],
        t_enqueue: Optional[float],
    ) -> IntermediateResult:
        with trace.span(
            "serverQuery", requestId=str(req.get("requestId") or ""), server=self.name
        ):
            if t_enqueue is not None:
                # FCFS queue wait, child of serverQuery: the scheduler
                # phase of the waterfall (metrics twin lives in
                # QueryScheduler.run as phase.schedulerWait)
                trace.add("queueWait", (time.monotonic() - t_enqueue) * 1000.0)
            tdm = self.data_manager.table(req["table"])
            if tdm is None:
                # fall through to the trace attach below: the span tree
                # for a misrouted query is exactly what an operator
                # debugging stale routing needs to see
                result = IntermediateResult(
                    exceptions=[
                        (ErrorCode.SERVER_SCHEDULER_DOWN, f"table {req['table']} not on server {self.name}")
                    ]
                )
                trace.event("tableNotHosted", table=req["table"])
                if trace.enabled:
                    result.trace.update(trace.to_dict())
                return result
            names: Optional[Sequence[str]] = req["segments"] or None
            acquired = tdm.acquire_segments(names)
            try:
                # honest degradation: requested segments this server cannot
                # serve right now (dropped, quarantined pending re-fetch…)
                # are REPORTED, not silently skipped — the broker re-covers
                # them on a replica or flips partialResponse /
                # numSegmentsUnserved for the client
                missing: List[str] = []
                if names:
                    held = {a.name for a in acquired}
                    missing = [n for n in names if n not in held]
                    if missing:
                        self.metrics.meter("segmentsMissedServing").mark(len(missing))
                views = [a.query_view() for a in acquired]
                if req.get("join"):
                    # distributed-join phase request (broker/joinplan.py):
                    # extraction or join execution over the local views,
                    # through the SAME fair-share scheduler slot this
                    # request already queued in — one tenant's join
                    # traffic is bounded exactly like its scans
                    result = self._process_join(
                        req, request, req["join"], views, deadline, trace
                    )
                    result.unserved_segments = missing
                    if trace.enabled:
                        result.trace.update(trace.to_dict())
                    return result
                if request.explain == "plan":
                    # EXPLAIN: the physical plan INSTEAD of execution —
                    # zero lane submissions, zero cost (safe to call in
                    # production; tier-1 guarded)
                    from pinot_tpu.engine.explain import build_explain_node

                    with trace.span("explainPlan", segments=len(acquired)):
                        node = build_explain_node(
                            self.executor, views, request, req["table"],
                            self.name, plan_stats=self.plan_stats,
                            result_cache=self.result_cache,
                        )
                    node["mode"] = "plan"
                    self.metrics.meter("plan.explains").mark()
                    result = IntermediateResult(
                        total_docs=int(node.get("totalDocs") or 0),
                        plan_info=[node],
                    )
                else:
                    # ingest-aware result cache: the key covers the
                    # exact staged data generation (segment names +
                    # process-unique staging tokens), so a hit is
                    # provably as fresh as re-executing — and costs
                    # zero device work.  Traced/EXPLAIN requests and
                    # partial covers bypass (key_for + the missing
                    # guard); results with exceptions are never stored.
                    ckey = None
                    cache = self.result_cache
                    if cache.enabled and not missing:
                        ckey = cache.key_for(request, views, req["table"])
                    result = cache.get(ckey) if ckey is not None else None
                    if result is not None:
                        # the hit executed nothing: the live span tree
                        # records the verdict instead of phase spans
                        trace.event("rescacheHit")
                    else:
                        with trace.span("planAndExecute", segments=len(acquired)):
                            result = self.executor.execute(
                                views, request, deadline=deadline
                            )
                        if ckey is not None and not result.exceptions:
                            cache.put(ckey, result)
                    if request.explain == "analyze":
                        # EXPLAIN ANALYZE: the prediction is built AFTER
                        # execution (so quarantine/compile state reflects
                        # what just happened) and annotated with actuals
                        # straight off this reply's cost vector — the
                        # per-node actuals sum EXACTLY to the broker's
                        # merged cost because only merged replies'
                        # plan nodes survive the gather
                        from pinot_tpu.engine.explain import (
                            _json_safe,
                            build_explain_node,
                        )

                        node = build_explain_node(
                            self.executor, views, request, req["table"],
                            self.name, plan_stats=self.plan_stats,
                            result_cache=self.result_cache,
                        )
                        node["mode"] = "analyze"
                        node["actualCost"] = _json_safe(dict(result.cost))
                        node["actualDocsScanned"] = int(result.num_docs_scanned)
                        dev_node = node.get("device")
                        if isinstance(dev_node, dict) and "batching" in dev_node:
                            # batching ACTUAL off this very execution:
                            # how many same-shape peers the launch
                            # carried.  (No actualCacheHit field:
                            # ANALYZE always executes — the cache is
                            # keyed off for explain modes — so the
                            # standing-entry probe `cacheHit` is the
                            # honest cache signal here.)
                            dev_node["batching"]["actualBatchSize"] = int(
                                getattr(result, "_batch_size", 1) or 1
                            )
                        result.plan_info = [node]
                if not missing:
                    # shadow-audit sampling hook (utils/audit.py): the
                    # held views pin the exact served snapshot; the
                    # offer itself is one counter increment for the
                    # non-sampled 1-in-N losers
                    self.auditor.offer(req, request, views, result)
                result.unserved_segments = missing
            finally:
                tdm.release_segments(acquired)
        if trace.enabled:
            result.trace.update(trace.to_dict())
        return result

    # -- distributed joins (engine/join.py + broker/joinplan.py) ------
    def _extract_bytes(self, views, columns) -> int:
        total = 0
        for seg in views:
            for c in columns:
                col = seg.columns.get(c)
                if col is not None and getattr(col, "fwd", None) is not None:
                    total += col.fwd.nbytes
        return total

    def _process_join(
        self, req: dict, request, jctx: dict, views, deadline, trace
    ) -> IntermediateResult:
        """One join-phase request: ``extract`` returns the side's
        matched rows as a dict-encoded exchange payload; ``exec`` runs
        the hash join (device kernel with host heal) over local and/or
        shipped sides and returns normal mergeable partials."""
        from pinot_tpu.engine import join as join_mod

        spec = request.join
        if spec is None:
            return IntermediateResult(
                exceptions=[
                    (ErrorCode.QUERY_EXECUTION, "join context on a non-join query")
                ]
            )
        phase = jctx.get("phase")
        t0 = time.perf_counter()
        try:
            left_f, right_f = join_mod.split_join_filter(request)
            left_cols, right_cols = join_mod.side_columns(request)
            if phase == "extract":
                side_name = jctx.get("side")
                if side_name == "build":
                    stripped = [spec.strip_right(c) for c in right_cols]
                    name_of = {spec.strip_right(c): c for c in right_cols}
                    rows, matched = join_mod.extract_side(
                        views, right_f, spec.right_key, stripped, name_of
                    )
                    read_cols = [spec.right_key, *stripped]
                else:
                    rows, matched = join_mod.extract_side(
                        views, left_f, spec.left_key, left_cols
                    )
                    read_cols = [spec.left_key, *left_cols]
                res = IntermediateResult(
                    num_docs_scanned=matched,
                    total_docs=sum(v.num_docs for v in views),
                    num_segments_queried=len(views),
                )
                res.add_cost(
                    hostMs=round((time.perf_counter() - t0) * 1000, 3),
                    bytesScanned=self._extract_bytes(views, read_cols),
                )
                res.join_payload = join_mod.encode_side(rows)
                self.metrics.meter("join.extracts").mark()
                self.executor._phase(
                    "joinExtract", t0, side=side_name, segments=len(views)
                )
                return res

            if phase != "exec":
                raise join_mod.JoinValidationError(
                    f"unknown join phase {phase!r}"
                )
            strategy = jctx.get("strategy")
            ckey = None
            cache = self.result_cache
            if strategy == "colocated":
                build_table = jctx.get("buildTable") or ""
                build_names = list(jctx.get("buildSegments") or ())
                tdm_b = self.data_manager.table(build_table)
                if tdm_b is None:
                    return IntermediateResult(
                        exceptions=[
                            (
                                ErrorCode.SERVER_SEGMENT_MISSING,
                                f"build table {build_table} not on server {self.name}",
                            )
                        ]
                    )
                b_acquired = tdm_b.acquire_segments(build_names or None)
                try:
                    held = {a.name for a in b_acquired}
                    miss_b = [n for n in build_names if n not in held]
                    if miss_b:
                        return IntermediateResult(
                            exceptions=[
                                (
                                    ErrorCode.SERVER_SEGMENT_MISSING,
                                    f"server {self.name}: build segments "
                                    f"unavailable: {sorted(miss_b)}",
                                )
                            ]
                        )
                    b_views = [a.query_view() for a in b_acquired]
                    # failover re-check: a child batch may land on a
                    # replica whose LOCAL build segments cover different
                    # partitions — serve only if every probe partition
                    # is locally buildable, else 230 so the broker
                    # re-covers elsewhere
                    probe_parts = {
                        join_mod.partition_of_segment(v.segment_name) for v in views
                    }
                    build_parts = {
                        join_mod.partition_of_segment(v.segment_name)
                        for v in b_views
                    }
                    if None in probe_parts or not probe_parts <= build_parts:
                        return IntermediateResult(
                            exceptions=[
                                (
                                    ErrorCode.SERVER_SEGMENT_MISSING,
                                    f"server {self.name}: local build side does "
                                    f"not cover probe partitions",
                                )
                            ]
                        )
                    # ingest-aware result cache, keyed on BOTH sides'
                    # segment sets + staging tokens: an ingest advance
                    # or segment change on EITHER table mints new
                    # tokens, so a stale joined answer is structurally
                    # unreachable (ISSUE 14 interop guard)
                    if cache.enabled:
                        ckey = cache.key_for_join(
                            request, views, b_views, req["table"], build_table
                        )
                    cached = cache.get(ckey) if ckey is not None else None
                    if cached is not None:
                        trace.event("rescacheHit")
                        return cached
                    result = self._join_exec(
                        request, spec, right_f, right_cols, b_views,
                        left_f, left_cols, views, deadline, trace,
                    )
                    result.num_segments_queried = len(views) + len(b_views)
                    if ckey is not None and not result.exceptions:
                        cache.put(ckey, result)
                finally:
                    tdm_b.release_segments(b_acquired)
            elif strategy == "broadcast":
                build = join_mod.decode_side(jctx["build"])
                result = self._join_exec(
                    request, spec, None, right_cols, None,
                    left_f, left_cols, views, deadline, trace, build=build,
                )
                result.num_segments_queried = len(views)
                bbytes = build.nbytes()
                result.add_cost(broadcastBytes=bbytes)
                self.metrics.meter("join.broadcastBytes").mark(bbytes)
            elif strategy == "shuffle":
                build = join_mod.decode_side(jctx["build"])
                probe = join_mod.decode_side(jctx["probe"])
                sbytes = build.nbytes() + probe.nbytes()
                with trace.span(
                    "joinExec", strategy="shuffle", buildRows=build.n,
                    probeRows=probe.n,
                ):
                    result = self.executor.execute_join(
                        request, build, probe, deadline=deadline
                    )
                result.add_cost(shuffleBytes=sbytes)
                self.metrics.meter("join.shuffleBytes").mark(sbytes)
            else:
                raise join_mod.JoinValidationError(
                    f"unknown join strategy {strategy!r}"
                )
            self.metrics.meter("join.execs").mark()
            self.metrics.meter("join.buildRows").mark(
                int(result.cost.get("buildRows", 0))
            )
            self.metrics.meter("join.probeRows").mark(
                int(result.cost.get("probeRows", 0))
            )
            return result
        except join_mod.JoinValidationError as e:
            # a typed client error, never a crash: the broker surfaces
            # it as QUERY_VALIDATION (4xx), and it is NOT retryable
            return IntermediateResult(
                exceptions=[(ErrorCode.QUERY_VALIDATION, str(e))]
            )

    def _join_exec(
        self, request, spec, right_f, right_cols, b_views,
        left_f, left_cols, views, deadline, trace, build=None,
    ) -> IntermediateResult:
        """Local probe-side extraction (+ build-side for colocated),
        then the healed hash join."""
        from pinot_tpu.engine import join as join_mod

        t0 = time.perf_counter()
        if build is None:
            stripped = [spec.strip_right(c) for c in right_cols]
            name_of = {spec.strip_right(c): c for c in right_cols}
            with trace.span("joinBuildLocal", segments=len(b_views)):
                build, _m = join_mod.extract_side(
                    b_views, right_f, spec.right_key, stripped, name_of
                )
        with trace.span("joinProbeLocal", segments=len(views)):
            probe, matched = join_mod.extract_side(
                views, left_f, spec.left_key, left_cols
            )
        self.metrics.timer("phase.joinExtract").update(
            (time.perf_counter() - t0) * 1000
        )
        with trace.span(
            "joinExec", buildRows=build.n, probeRows=probe.n
        ):
            result = self.executor.execute_join(
                request, build, probe, deadline=deadline
            )
        return result
