"""Server-side data managers: instance -> table -> segment hierarchy with
refcounted acquire/release.

Mirrors the reference hierarchy (``InstanceDataManager.java:29``,
``AbstractTableDataManager.java:42``, ``SegmentDataManager``): queries
acquire segments (refcount++) before executing and release after, so a
segment swap/drop never unmaps data under a running query.  Dropping a
segment marks it dead; actual removal happens when the last reader
releases.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from pinot_tpu.segment.immutable import ImmutableSegment


class SegmentDataManager:
    def __init__(self, segment) -> None:
        # ImmutableSegment, or a MutableSegment (consuming) whose
        # .snapshot() yields the queryable view at the row watermark
        self.segment = segment
        self._refcount = 1  # owner reference
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self.segment.segment_name

    def query_view(self) -> ImmutableSegment:
        snap = getattr(self.segment, "snapshot", None)
        return snap() if callable(snap) else self.segment

    def acquire(self) -> bool:
        with self._lock:
            if self._refcount <= 0:
                return False
            self._refcount += 1
            return True

    def release(self) -> int:
        with self._lock:
            self._refcount -= 1
            rc = self._refcount
        if rc == 0:
            # last reference gone: return postings bytes to the
            # process-wide inverted-index budget
            from pinot_tpu.segment.invindex import release_postings

            release_postings(self.segment)
        return rc


class TableDataManager:
    """Per-table segment registry (AbstractTableDataManager analog)."""

    def __init__(self, table_name: str) -> None:
        self.table_name = table_name
        self._segments: Dict[str, SegmentDataManager] = {}
        self._lock = threading.Lock()

    def add_segment(self, segment) -> None:
        # integrity note: the disk-load CRC gate lives one layer up in
        # ServerInstance.add_segment(verify_crc=True) — it must run
        # BEFORE default-column injection, which this layer can't order
        name = segment.segment_name if hasattr(segment, "segment_name") else segment.metadata.segment_name
        with self._lock:
            old = self._segments.get(name)
            self._segments[name] = SegmentDataManager(segment)
        if old is not None:
            old.release()  # drop owner ref of the replaced segment

    def remove_segment(self, name: str) -> None:
        with self._lock:
            sdm = self._segments.pop(name, None)
        if sdm is not None:
            sdm.release()

    def segment_names(self) -> List[str]:
        with self._lock:
            return list(self._segments.keys())

    def acquire_segments(
        self, names: Optional[Sequence[str]] = None
    ) -> List[SegmentDataManager]:
        """Acquire the named segments (all if None); missing names are
        skipped — the reference reports them as partial results."""
        with self._lock:
            targets = (
                [self._segments[n] for n in names if n in self._segments]
                if names is not None
                else list(self._segments.values())
            )
        return [s for s in targets if s.acquire()]

    def release_segments(self, acquired: Sequence[SegmentDataManager]) -> None:
        for s in acquired:
            s.release()


class InstanceDataManager:
    def __init__(self) -> None:
        self._tables: Dict[str, TableDataManager] = {}
        self._lock = threading.Lock()

    def table(self, name: str, create: bool = False) -> Optional[TableDataManager]:
        with self._lock:
            tdm = self._tables.get(name)
            if tdm is None and create:
                tdm = TableDataManager(name)
                self._tables[name] = tdm
            return tdm

    def add_segment(self, table_name: str, segment: ImmutableSegment) -> None:
        self.table(table_name, create=True).add_segment(segment)

    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())
