"""Fleet plan prewarming: compile the hot working set BEFORE it serves.

A restarted server (rollout, rebalance destination, crash recovery)
starts with empty lane compile registries: every plan shape pays its
first-launch compile on a live query.  The persistent compile cache
(``engine/compilecache.py``) makes that compile cheap when the shape ran
here before; this worker makes it *invisible* — at segment-load time the
server pulls the fleet's top-K plan shapes for the tables it hosts
(broker/controller ``/debug/workload``), rebuilds digest-exact phantom
staged metadata (``engine/explain.build_prewarm_spec`` — zero real
staging, zero HBM), and drives the XLA compiles on this background
thread.  The serving lane is never entered: the AOT compile populates
the persistent cache (and, in-process, XLA's own executable cache) so
the first real query re-traces in milliseconds and is counted
``compile.warm``/``compile.prewarmed`` — never ``compile.cold``, never
tripping the lane stall watchdog.

Readiness contract: ``request_prewarm`` flips the worker to *warming*
synchronously; the state returns to *ready* when the pass drains or the
deadline (``PINOT_TPU_PREWARM_TIMEOUT_S``) expires.  The networked
starter reports the flag on every heartbeat; brokers deprioritize (never
exclude) warming replicas, and the rebalancer's trim waits for the
destination to finish warming before the old replica is dropped.

Knobs: ``PINOT_TPU_PREWARM_TOP_K`` (shapes pulled per pass, default 8;
0 disables), ``PINOT_TPU_PREWARM_TIMEOUT_S`` (pass deadline, default
30s).  No workload source wired (plain in-process instances) means the
worker never starts and the server is simply always ready.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

# every worker that ever started a thread, for the test-suite leak
# guard (workers still serving are exempt — a STOPPED worker whose
# thread survives is the leak, matching the other conftest guards)
_workers: List["PrewarmWorker"] = []
_workers_lock = threading.Lock()


def leaked_prewarm_threads(grace_s: float = 2.0) -> List[str]:
    """Names of prewarm threads of STOPPED workers still alive after
    ``grace_s`` of joining (conftest guard: ``stop()`` must actually
    end the worker).  Workers still serving (live servers held by
    module-scoped fixtures) are exempt."""
    deadline = time.monotonic() + grace_s
    leaked: List[str] = []
    with _workers_lock:
        workers = list(_workers)
    for w in workers:
        t = w._thread
        if t is None or not w._stop.is_set():
            continue
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            leaked.append(t.name)
    return leaked


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class PrewarmWorker:
    """Background compile driver for one ``ServerInstance``.

    ``workload_source(tables, n)`` is the pluggable fleet-workload feed:
    it returns plan-stat entries (``utils/planstats`` ``_entry_dict``
    shape — ``exemplarPql`` + ``table`` are what matters here) ranked
    hottest-first, already filtered to ``tables``.  The in-process
    starter feeds it from the local broker's registry; the networked
    starter fetches the controller's fleet roll-up over HTTP.
    """

    def __init__(
        self,
        instance,
        workload_source: Optional[Callable[[List[str], int], List[dict]]] = None,
        top_k: Optional[int] = None,
        timeout_s: Optional[float] = None,
    ) -> None:
        self.instance = instance
        self.workload_source = workload_source
        self.top_k = (
            top_k
            if top_k is not None
            else _env_int("PINOT_TPU_PREWARM_TOP_K", 8)
        )
        self.timeout_s = (
            timeout_s
            if timeout_s is not None
            else _env_float("PINOT_TPU_PREWARM_TIMEOUT_S", 30.0)
        )
        self.metrics = instance.metrics
        for m in (
            "prewarm.shapes", "prewarm.compiled",
            "prewarm.skipped", "prewarm.failed",
        ):
            self.metrics.meter(m)
        self._warming = False
        self._last_pass_ms: Optional[float] = None
        self._trigger = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.metrics.gauge("server.warming").set_fn(
            lambda: 1 if self._warming else 0
        )

    # -- lifecycle ----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.workload_source is not None and self.top_k > 0

    def request_prewarm(self, table: Optional[str] = None) -> None:
        """Ask for a prewarm pass (segment load / registration / table
        assignment).  Flips to *warming* synchronously — the next
        heartbeat already reports it — and wakes the worker; triggers
        arriving during a pass coalesce into one follow-up pass."""
        if not self.enabled or self._stop.is_set():
            return
        with self._lock:
            self._warming = True
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run,
                    name=f"prewarm-{self.instance.name}",
                    daemon=True,
                )
                with _workers_lock:
                    _workers.append(self)
                self._thread.start()
        self._trigger.set()

    def stop(self) -> None:
        self._stop.set()
        self._trigger.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        with self._lock:
            self._warming = False

    # -- readiness surface --------------------------------------------
    @property
    def warming(self) -> bool:
        return self._warming

    def state(self) -> dict:
        return {
            "warming": self._warming,
            "ready": not self._warming,
            "enabled": self.enabled,
            "topK": self.top_k,
            "timeoutS": self.timeout_s,
            "lastPassMs": self._last_pass_ms,
            "compiled": self.metrics.meter("prewarm.compiled").count,
            "skipped": self.metrics.meter("prewarm.skipped").count,
            "failed": self.metrics.meter("prewarm.failed").count,
        }

    # -- worker -------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._trigger.wait(timeout=0.5):
                continue
            # debounce: segment loads arrive in bursts; let the burst
            # settle so one pass covers the whole assignment
            self._stop.wait(0.05)
            self._trigger.clear()
            if self._stop.is_set():
                break
            t0 = time.perf_counter()
            try:
                self._pass()
            except Exception:
                # the worker must never die on a feed/compile surprise —
                # a failed pass just means colder first queries
                logger.exception("prewarm pass failed")
                self.metrics.meter("prewarm.failed").mark()
            self._last_pass_ms = round((time.perf_counter() - t0) * 1000.0, 3)
            if not self._trigger.is_set():
                # no new trigger arrived during the pass: warmed up
                self._warming = False

    def _hosted_tables(self) -> List[str]:
        raw = {
            self.instance._raw_table(t)
            for t in self.instance.data_manager.table_names()
        }
        return sorted(raw)

    def _pass(self) -> None:
        deadline = time.monotonic() + max(0.1, self.timeout_s)
        tables = self._hosted_tables()
        if not tables:
            return
        try:
            entries = self.workload_source(tables, self.top_k) or []
        except Exception as e:
            logger.warning("prewarm workload fetch failed: %s", e)
            self.metrics.meter("prewarm.failed").mark()
            return
        capped = entries[: self.top_k]
        for i, entry in enumerate(capped):
            if self._stop.is_set():
                return
            if time.monotonic() >= deadline:
                # deadline-capped: whatever is left compiles lazily on
                # the serving path (honestly counted there)
                remaining = len(capped) - i
                self.metrics.meter("prewarm.skipped").mark(max(1, remaining))
                logger.warning(
                    "prewarm deadline (%.1fs) hit with %d shapes left",
                    self.timeout_s, remaining,
                )
                return
            self.metrics.meter("prewarm.shapes").mark()
            try:
                if not self._prewarm_entry(entry):
                    self.metrics.meter("prewarm.skipped").mark()
            except Exception as e:
                logger.warning(
                    "prewarm failed for shape %s: %s",
                    entry.get("digest", "?"), e,
                )
                self.metrics.meter("prewarm.failed").mark()

    def _prewarm_entry(self, entry: dict) -> bool:
        """Compile one workload entry's exemplar shape.  Returns True
        when a compile actually happened (False: nothing to do — no
        exemplar, table not hosted here, shape already compiled, or the
        plan legitimately runs off-device)."""
        pql = entry.get("exemplarPql") or ""
        if not pql:
            return False
        from pinot_tpu.engine.explain import build_prewarm_spec
        from pinot_tpu.pql import optimize_request, parse_pql

        request = optimize_request(parse_pql(pql))
        if request.explain:
            return False
        raw = self.instance._raw_table(request.table_name)
        compiled_any = False
        for tname in self.instance.data_manager.table_names():
            if self.instance._raw_table(tname) != raw:
                continue
            tdm = self.instance.data_manager.table(tname)
            if tdm is None:
                continue
            acquired = tdm.acquire_segments()
            try:
                views = [a.query_view() for a in acquired]
                spec = build_prewarm_spec(self.instance.executor, views, request)
            finally:
                tdm.release_segments(acquired)
            if spec is None:
                continue
            # the AOT compile runs HERE, on this background thread —
            # the serving lane is never entered, so prewarm can never
            # stall a live launch or trip the watchdog.  The lowered
            # avals were built from metadata only; the compile needs no
            # segment data, so the segments are already released.
            t0 = time.perf_counter()
            spec["compile"]()
            compile_ms = (time.perf_counter() - t0) * 1000.0
            if spec["lane"].record_prewarmed(spec["planDigest"], compile_ms):
                self.metrics.meter("prewarm.compiled").mark()
                compiled_any = True
        return compiled_any
