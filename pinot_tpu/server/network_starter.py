"""Networked server starter: a server process joining a remote controller.

The in-process ``ServerStarter`` receives transitions as direct
callbacks; this variant is the real-deployment analog of
``HelixServerStarter.java:63`` + ``SegmentFetcherAndLoader.java:84``:

- register with the controller over HTTP (PARTICIPANT join),
- heartbeat for liveness (the ZK session),
- poll transition messages, execute them (download segment bytes from
  the controller's store with CRC skip, load into the query engine, or
  drop), ack the resulting state,
- serve broker queries on a length-framed TCP socket.

All state the controller needs rides in the register/ack payloads; the
server keeps a local segment cache under ``data_dir`` so a restart with
matching CRCs skips downloads.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from pinot_tpu.common.schema import Schema
from pinot_tpu.controller.resource_manager import CONSUMING, DROPPED, OFFLINE, ONLINE
from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.segment.format import (
    SEGMENT_FILE_NAME,
    SegmentIntegrityError,
    SegmentStaleError,
    read_segment,
    verify_segment_crc,
)
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.transport.tcp import TcpServer

logger = logging.getLogger(__name__)


class ServerAdminHttpServer:
    """Server-side observability HTTP surface (the reference server's
    admin-application analog): ``/health``, Prometheus text at
    ``/metrics``, the full status/metrics JSON at ``/debug/metrics``,
    per-plan stats at ``/debug/plans``, the device-utilization
    snapshot at ``/debug/device``, the mesh topology + per-lane
    dispatch stats at ``/debug/mesh``, and the on-demand profiler bracket
    at ``POST /debug/profile/start|stop`` (``GET /debug/profile`` for
    state).  The query data plane stays on the framed TCP socket; this
    port is scrape/ops-only.  The networked starter advertises it to
    the controller as the instance URL so the dashboard can aggregate
    a cluster-wide metrics snapshot."""

    def __init__(self, server: ServerInstance, host: str = "127.0.0.1", port: int = 0):
        inst = server

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, body: bytes, ctype: str, status: int = 200) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, payload, status: int = 200) -> None:
                self._send(
                    json.dumps(payload).encode("utf-8"), "application/json", status
                )

            def do_GET(self):
                if self.path == "/health":
                    return self._send(b'{"status": "ok"}', "application/json")
                if self.path == "/metrics":
                    return self._send(
                        inst.metrics_text().encode("utf-8"),
                        "text/plain; version=0.0.4",
                    )
                if self.path == "/debug/metrics":
                    return self._send(
                        json.dumps(inst.status()).encode("utf-8"),
                        "application/json",
                    )
                if self.path == "/debug/device":
                    # utilization snapshot alone (status() minus the
                    # heavyweight sections): the controller rollup and
                    # dashboards poll this cheaply
                    return self._send_json(inst.device_utilization())
                if self.path == "/debug/mesh":
                    # mesh execution plane (engine/mesh.py): topology
                    # snapshot + per-lane dispatch stats — which chip
                    # group serves which lane, rolled up
                    return self._send_json(
                        {
                            "topology": inst.topology.snapshot(),
                            "lanes": None
                            if inst.lanes is None
                            else inst.lanes.stats(),
                        }
                    )
                if self.path == "/debug/profile":
                    return self._send_json(inst.profiler.snapshot())
                if self.path == "/debug/prewarm":
                    # warm-start readiness surface (server/prewarm.py):
                    # warming/ready flag + pass counters
                    return self._send_json(inst.prewarm.state())
                if self.path == "/debug/flightrec":
                    return self._send_json(inst.flightrec.snapshot())
                if self.path == "/debug/residency":
                    # tiered residency plane (engine/residency.py):
                    # per-tier bytes/entries, cap pressure, and the
                    # demotion/promotion cycle counters
                    from pinot_tpu.engine.residency import RESIDENCY

                    return self._send_json(RESIDENCY.snapshot())
                if self.path == "/debug/segments":
                    # per-segment CRC map for the controller's
                    # cross-replica checksum sweep (CrcAuditManager)
                    return self._send_json(inst.segment_crcs())
                if (
                    self.path.startswith("/segments/")
                    and self.path.endswith("/copy")
                ):
                    # reverse replication donor: the DeepStoreScrubber
                    # repairing a lost/corrupt deep-store copy pulls the
                    # verified bytes of this server's replica
                    p = self.path.strip("/").split("/")
                    if len(p) == 4:
                        data = inst.segment_copy_bytes(p[1], p[2])
                        if data is not None:
                            return self._send(data, "application/octet-stream")
                    return self._send(
                        b'{"error": "segment not donatable"}',
                        "application/json",
                        404,
                    )
                if self.path == "/debug/audit":
                    # shadow-audit plane (utils/audit.py): sampler
                    # counters, quarantined (digest, tier) pairs, and
                    # the recent-divergence ring
                    return self._send_json(inst.audit_snapshot())
                from urllib.parse import parse_qs, urlparse

                url = urlparse(self.path)
                if url.path == "/debug/history":
                    # bounded metric time series (utils/timeseries.py):
                    # ?series= comma-separated name prefixes, ?windowS=
                    # trailing window in seconds
                    return self._send_json(
                        inst.history.query_from_qs(url.query)
                    )
                if url.path == "/debug/plans":
                    # per-plan-digest workload stats (utils/planstats.py);
                    # ?by=cost reorders the top-K by total work instead
                    # of frequency
                    qs = parse_qs(url.query)
                    by = (qs.get("by") or ["count"])[0]
                    try:
                        top = int((qs.get("top") or ["50"])[0])
                    except ValueError:
                        top = 50
                    return self._send(
                        json.dumps(
                            inst.plan_stats.snapshot(top=top, by=by)
                        ).encode("utf-8"),
                        "application/json",
                    )
                self._send(b'{"error": "not found"}', "application/json", 404)

            def do_POST(self):
                from pinot_tpu.server.profiler import ProfilerUnavailableError

                n = int(self.headers.get("Content-Length", "0") or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else {}
                except ValueError:
                    return self._send_json({"error": "bad JSON body"}, 400)
                if self.path == "/debug/profile/start":
                    try:
                        return self._send_json(
                            inst.profile_start(body.get("timeoutS"))
                        )
                    except ProfilerUnavailableError as e:
                        # typed 404: THIS backend has no usable profiler
                        # — distinct from an unknown route or bad input
                        return self._send_json(
                            {
                                "error": str(e),
                                "errorType": "ProfilerUnavailableError",
                            },
                            404,
                        )
                    except Exception as e:
                        return self._send_json({"error": str(e)}, 500)
                if self.path == "/debug/profile/stop":
                    return self._send_json(inst.profile_stop())
                self._send_json({"error": "not found"}, 404)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class RemoteConsumer:
    """Server-process-side LLC consumer: pulls rows from the stream
    broker by offset, indexes into a mutable segment served to queries
    immediately, and runs the completion protocol against the
    controller over HTTP (the ``LLRealtimeSegmentDataManager.java:68``
    consume loop + ``SegmentCompletionProtocol`` client).

    Since r15 consumers are COOPERATIVE: instead of one dedicated
    thread per consuming segment (which melts down at 100+ tables),
    each consumer exposes ``step()`` — one bounded, never-blocking unit
    of consume/commit work — and the starter's shared
    ``IngestConsumerPool`` (``PINOT_TPU_INGEST_CONSUMERS`` workers)
    drives all of them.  Every wait the old loop slept through
    (backpressure pause, empty stream, completion HOLD, controller
    freeze) now surfaces as the step's return delay, so a frozen
    partition costs zero worker time and N hot partitions genuinely
    consume in parallel."""

    def __init__(
        self,
        starter: "NetworkedServerStarter",
        table: str,
        segment: str,
        msg: Dict[str, Any],
        poll_interval_s: float = 0.2,
    ) -> None:
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.realtime.mutable import MutableSegment
        from pinot_tpu.realtime.stream import stream_from_descriptor

        self.starter = starter
        self.table = table
        self.segment = segment
        self.partition = int(msg.get("partition", 0))
        self.offset = int(msg.get("startOffset", 0))
        self.rows_per_segment = int(msg.get("rowsPerSegment", 100_000))
        self.poll_interval_s = poll_interval_s
        self.stream = stream_from_descriptor(msg["streamDescriptor"])
        schema = Schema.from_json(msg["schemaJson"])
        self.mutable = MutableSegment(schema, segment, table)
        self.mutable.start_offset = self.offset
        self._stop = threading.Event()
        # controller unreachability is a FREEZE, not a failure: offsets
        # hold, the consumer survives, and retries back off with full
        # jitter (utils/retry.py) so a healing controller is not
        # stampeded by every frozen consumer at once.  The backoff's
        # delay parks this consumer in the pool (``_park_s``) instead
        # of blocking a shared worker.
        from pinot_tpu.utils.retry import FullJitterBackoff

        self._ctrl_backoff = FullJitterBackoff(
            initial_s=max(0.1, poll_interval_s), cap_s=5.0
        )
        # seconds the NEXT pool step should wait before re-driving this
        # consumer; set by the protocol paths (HOLD/freeze) per round
        self._park_s = poll_interval_s
        # ingest observability (same series as the in-process consumer,
        # realtime/llc.py): per-partition lag gauge + rows/s meter.
        # The TTL-cached probe (realtime/stream.py LagProbe) keeps the
        # stream-broker RPC off the metrics-scrape path.
        from pinot_tpu.realtime.stream import LagProbe

        self._metrics = getattr(starter.server, "metrics", None)
        self._lag_probe = LagProbe(self.stream, self.partition, lambda: self.offset)
        self._lag_gauge_name = f"ingest.lag.{table}.p{self.partition}"
        # ingest backpressure: the hosting server's watermark governor
        # pauses consumption above the HBM/mutable high watermark; the
        # per-consumer paused gauge makes the held partition visible
        self._governor = getattr(starter.server, "ingest_backpressure", None)
        self._paused = False
        self._paused_gauge_name = f"ingest.paused.{table}.p{self.partition}"
        self._paused_fn = lambda: 1 if self._paused else 0
        # event-time freshness (broker/freshness.py): this consumer
        # advances the per-(table, partition) watermark from the schema
        # time column as it indexes — the same series the in-process
        # consumer (realtime/llc.py) reports, keyed so rollover and
        # pool resizes keep it continuous
        from pinot_tpu.broker.freshness import WATERMARKS, now_ms
        from pinot_tpu.common.schema import time_unit_to_millis

        self._time_col = schema.time_column_name
        self._time_unit_ms = (
            time_unit_to_millis(schema.time_field.time_unit)
            if schema.time_field is not None
            else None
        )
        self._freshness_gauge_name = f"freshness.lag.{table}.p{self.partition}"

        def _freshness_probe(_t=table, _p=self.partition):
            w = WATERMARKS.get(_t, _p)
            return round(max(0.0, now_ms() - w), 3) if w is not None else 0

        self._freshness_fn = _freshness_probe
        if self._metrics is not None:
            lag_key = f"{table}.p{self.partition}"
            self._metrics.gauge(f"ingest.lag.{lag_key}").set_fn(self._lag_probe)
            self._metrics.gauge(f"ingest.paused.{lag_key}").set_fn(self._paused_fn)
            if self._time_col is not None:
                self._metrics.gauge(f"freshness.lag.{lag_key}").set_fn(
                    self._freshness_fn
                )

    def lag(self) -> Optional[int]:
        return self._lag_probe()

    def _detach_lag_gauge(self) -> None:
        """Stop reporting lag once this consumer is done: a frozen
        offset would otherwise read as phantom ever-growing lag when
        the partition's successor lives on another server.  clear_fn's
        equality guard makes this a no-op if a rolled successor on this
        server already owns the series."""
        if self._metrics is not None:
            self._metrics.gauge(self._lag_gauge_name).clear_fn(self._lag_probe)
            self._metrics.gauge(self._paused_gauge_name).clear_fn(self._paused_fn)
            self._metrics.gauge(self._freshness_gauge_name).clear_fn(
                self._freshness_fn
            )

    def start(self) -> None:
        self.starter.server.add_segment(self.table, self.mutable)
        self.starter.ingest_pool.add(self, key=self.segment)

    def stop(self) -> None:
        self._stop.set()
        self._detach_lag_gauge()

    # -- consume loop ---------------------------------------------------
    def _consume_to(self, limit_rows: int) -> int:
        budget = limit_rows - self.mutable.num_docs
        if budget <= 0:
            return 0
        if self._governor is not None:
            # bounded in-flight batches: one governor decision covers at
            # most max_batch_rows of exposure (the r6 path fetched a
            # whole segment budget in ONE call)
            budget = self._governor.clamp_batch(budget)
        rows, next_offset = self.stream.fetch(self.partition, self.offset, budget)
        self.mutable.index_batch(rows)
        if rows and self._time_col is not None and self._time_unit_ms is not None:
            from pinot_tpu.broker.freshness import WATERMARKS, batch_max_event_ms

            event_ms = batch_max_event_ms(
                [r.get(self._time_col) for r in rows if self._time_col in r],
                self._time_unit_ms,
            )
            if event_ms is not None:
                WATERMARKS.advance(self.table, self.partition, event_ms)
        advanced = next_offset != self.offset
        self.offset = next_offset
        self.mutable.end_offset = next_offset
        if rows and self._metrics is not None:
            self._metrics.meter("ingest.rowsConsumed").mark(len(rows))
        if advanced:
            # result-cache watermark hook (engine/rescache.py): cached
            # answers over the previous consume offset are superseded
            cache = getattr(self.starter.server, "result_cache", None)
            if cache is not None and cache.enabled:
                cache.on_offset_advance(self.table, self.partition, self.offset)
        return len(rows)

    def step(self) -> Optional[float]:
        """One cooperative pool unit: a bounded consume batch plus (at
        the row threshold) one completion-protocol round.  Returns the
        seconds until this consumer is eligible again, or None when the
        segment is finished (committed/discarded/stopped) — the
        CONSUMING transition for the next sequence registers a fresh
        consumer under the same per-(table, partition) gauge names."""
        if self._stop.is_set():
            self._detach_lag_gauge()
            return None
        if self._governor is not None:
            allowed = self._governor.consume_allowed()
            self._paused = not allowed
            if not allowed:
                # held above a memory watermark: offset freezes, lag
                # grows on the gauge, nothing is lost — consumption
                # resumes below the low watermark
                return self.poll_interval_s
        try:
            got = self._consume_to(self.rows_per_segment)
        except Exception as e:
            logger.warning("stream fetch failed for %s: %s", self.segment, e)
            return self.poll_interval_s
        if self.mutable.num_docs >= self.rows_per_segment:
            self._park_s = self.poll_interval_s
            if self._completion_round():
                # finished: this consumer's offset is frozen, so its
                # lag series must not keep reporting; a rolled
                # successor re-registers the same name
                self._detach_lag_gauge()
                return None
            return self._park_s
        return 0.0 if got else self.poll_interval_s

    def _freeze(self, why: str, err) -> bool:
        """Controller unreachable (or authority lost) mid-protocol:
        freeze the round — offset untouched, consumer alive — and park
        for a full-jitter backoff before the pool retries it."""
        self._park_s = self._ctrl_backoff.next_delay()
        logger.warning(
            "%s for %s frozen (retry in %.2fs): %s",
            why, self.segment, self._park_s, err,
        )
        return False

    def _completion_round(self) -> bool:
        """One segmentConsumed exchange; True when this consumer is
        done.  Never blocks — idle verdicts (HOLD, freeze, failed
        commit) set ``_park_s`` and return False so the pool re-drives
        this consumer after the delay."""
        lease = self.starter.server.lease
        if not lease.held():
            # write authority expired (partitioned past the lease
            # window): no segmentConsumed/commit until it renews — the
            # live controller may be re-electing a committer right now
            if self._metrics is not None:
                self._metrics.meter("lease.blockedCommits").mark()
            return self._freeze("completion round", "serving lease expired")
        epoch = lease.epoch if lease.granted else None
        try:
            out = self.starter._post(
                "/realtime/consumed",
                {
                    "segment": self.segment,
                    "server": self.starter.name,
                    "offset": self.offset,
                    "epoch": epoch,
                },
            )
        except Exception as e:
            return self._freeze("segmentConsumed", e)
        self._ctrl_backoff.reset()
        resp = out.get("response")
        target = out.get("targetOffset")
        if resp == "COMMIT":
            try:
                return self._commit(epoch)
            except Exception as e:
                # conversion/serialization failure: stay alive and retry
                # via the next segmentConsumed round
                logger.warning("commit of %s failed: %s", self.segment, e)
                self._park_s = self.poll_interval_s
                return False
        if resp == "CATCH_UP" and target is not None:
            while self.offset < int(target) and not self._stop.is_set():
                try:
                    got = self._consume_to(
                        self.rows_per_segment + int(target) - self.offset
                    )
                except Exception as e:
                    # transient stream failure mid-catch-up: keep the
                    # consumer alive, retry on the next round
                    logger.warning("catch-up fetch failed for %s: %s", self.segment, e)
                    self._park_s = self.poll_interval_s
                    return False
                if got == 0:
                    # stream has no more rows toward the target yet:
                    # yield the worker, resume catching up next step
                    self._park_s = self.poll_interval_s
                    return False
            return False
        if resp == "DISCARD":
            # another replica committed a different offset range: drop
            # local rows; the ONLINE transition will download the
            # committed copy
            self.starter.server.remove_segment(self.table, self.segment)
            return True
        if resp == "KEEP":
            # committed elsewhere at exactly our offset; keep serving
            # the local rows until the ONLINE transition replaces them
            return True
        # HOLD (or unknown): retry after the poll cadence
        self._park_s = self.poll_interval_s
        return False

    def _commit(self, epoch=None) -> bool:
        t0 = time.perf_counter()
        committed = self.mutable.to_committed_segment()
        path = f"/realtime/commit/{self.segment}/{self.starter.name}"
        if epoch is not None:
            # the lease epoch fences this upload: a controller failover
            # mid-upload typed-rejects it (409 StaleEpochError) instead
            # of double-committing into the new incarnation
            path += f"?epoch={epoch}"
        try:
            out = self.starter.upload_segment_bytes(path, committed)
        except Exception as e:
            # unreachable mid-upload: freeze-and-retry (the controller
            # may have persisted the copy and lost only the reply — the
            # next segmentConsumed answers KEEP/DISCARD idempotently)
            return self._freeze("segmentCommit", e)
        if out.get("response") != "KEEP":
            # NOT_LEADER / HOLD (commit already being persisted by a
            # prior attempt, or our lease/leadership was fenced away):
            # retry via the next segmentConsumed round
            return False
        if self._metrics is not None:
            self._metrics.timer("ingest.commitMs").update(
                (time.perf_counter() - t0) * 1000
            )
        logger.info("committed %s at offset %d", self.segment, self.offset)
        return True


class HLRemoteConsumer:
    """High-level-consumer ingestion for one server (the
    ``HLRealtimeSegmentDataManager.java:54`` analog): this server is
    one member of the table's consumer group; the stream broker assigns
    it partitions and rebalances on membership change.  Rows index into
    a server-owned mutable segment; at the row threshold the segment
    converts and uploads pinned to this server, group offsets commit,
    and consumption rolls locally to the next sequence (no committer
    election — HLC segments have exactly one owner).  Delivery is
    at-least-once across rebalances, as in the reference."""

    rolls_locally = True  # ONLINE of a sealed HLC segment must not stop us

    def __init__(self, starter: "NetworkedServerStarter", table: str, segment: str, msg: Dict[str, Any]) -> None:
        from pinot_tpu.realtime.llc import parse_segment_name
        from pinot_tpu.realtime.netstream import HLConsumer

        self.starter = starter
        self.table = table
        self.segment = segment
        _, self.idx, self.seq = parse_segment_name(segment)
        self.rows_per_segment = int(msg.get("rowsPerSegment", 100_000))
        self.poll_interval_s = float(msg.get("pollIntervalS", 0.2))
        desc = msg["streamDescriptor"]
        if desc.get("type") == "kafka":
            # consumer groups over the Kafka wire protocol (0.9+ group
            # coordinator APIs, realtime/kafka_group.py)
            from pinot_tpu.realtime.kafka_group import KafkaGroupConsumer as _Consumer
        else:
            _Consumer = HLConsumer
        self.consumer = _Consumer(
            desc["host"], int(desc["port"]), desc["topic"],
            group=table, consumer_id=starter.name,
            session_timeout=float(msg.get("sessionTimeoutS", 10.0)),
        )
        self.consumer.on_revoke = self._on_revoke
        self.schema = Schema.from_json(msg["schemaJson"])
        self.mutable = MutableSegment(self.schema, segment, table)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.starter.server.add_segment(self.table, self.mutable)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.consumer.close()
        except Exception:
            pass

    def _run(self) -> None:
        try:
            joined = False
            while not self._stop.is_set():
                if not joined:
                    try:
                        self.consumer.join()
                        joined = True
                    except Exception as e:
                        # stream broker not reachable yet: keep trying —
                        # a one-shot join would strand the consumer
                        logger.warning("HLC join failed for %s: %s", self.segment, e)
                        self._stop.wait(self.poll_interval_s)
                        continue
                try:
                    budget = self.rows_per_segment - self.mutable.num_docs
                    rows = self.consumer.poll() if budget > 0 else []
                except Exception as e:
                    logger.warning("HLC poll failed for %s: %s", self.segment, e)
                    self._stop.wait(self.poll_interval_s)
                    continue
                self.mutable.index_batch([row for _, row in rows])
                if self.mutable.num_docs >= self.rows_per_segment:
                    if not self._seal_and_roll():
                        self._stop.wait(self.poll_interval_s)
                elif not rows:
                    self._stop.wait(self.poll_interval_s)
        except Exception:
            logger.exception("HLC consumer for %s died", self.segment)

    def _on_revoke(self) -> None:
        """Rebalance revoked (part of) our assignment: uncommitted rows
        must become durable before a successor resumes, so seal + upload
        + commit now (tiny segments are fine; rebalances are rare).  If
        the upload fails, DISCARD the uncommitted rows instead — they
        stay uncommitted, so the successor re-reads them; keeping them
        in our mutable would double-count."""
        if self.mutable.num_docs == 0:
            try:
                self.consumer.commit()
            except Exception as e:
                # every consumed row is already durable in sealed
                # segments; a failed commit only means a successor
                # re-reads from older committed offsets (at-least-once)
                logger.warning("HLC revoke-time offset commit failed: %s", e)
            return
        try:
            sealed = self._seal_and_roll()
        except Exception:
            # e.g. to_committed_segment() failed: fall through to the
            # discard path — the hook must leave the member in a known
            # state rather than raise into the consumer
            logger.exception("HLC seal during revoke failed for %s", self.segment)
            sealed = False
        if not sealed:
            old = self.segment
            self.mutable = MutableSegment(self.schema, self.segment, self.table)
            self.starter.server.add_segment(self.table, self.mutable)
            # the discarded rows were never persisted NOR committed:
            # roll positions back to committed so whoever owns these
            # partitions next (possibly still us) re-fetches them
            try:
                self.consumer.reset_to_committed()
            except Exception as e:
                logger.warning("HLC position rollback failed: %s", e)
            logger.warning(
                "HLC revoke: upload failed; discarded uncommitted rows of %s", old
            )

    def _seal_and_roll(self) -> bool:
        import urllib.parse

        from pinot_tpu.realtime.llc import make_segment_name

        committed = self.mutable.to_committed_segment()
        try:
            self.starter.upload_segment_bytes(
                f"/segments/{urllib.parse.quote(self.table)}?server={self.starter.name}",
                committed,
            )
        except Exception as e:
            logger.warning("HLC upload of %s failed (will retry): %s", self.segment, e)
            return False
        # segment durable on the controller: checkpoint group offsets,
        # then continue on the next sequence (at-least-once on a crash
        # between upload and commit — the reference's HLC contract)
        try:
            self.consumer.commit()
        except Exception as e:
            logger.warning("HLC offset commit failed for %s: %s", self.segment, e)
        old = self.segment
        self.seq += 1
        self.segment = make_segment_name(self.table, self.idx, self.seq)
        self.mutable = MutableSegment(self.schema, self.segment, self.table)
        # re-key BEFORE notifying the controller so the CONSUMING
        # transition for the new name dedupes against this consumer
        self.starter._consumers.pop(old, None)
        self.starter._consumers[self.segment] = self
        self.starter.server.add_segment(self.table, self.mutable)
        try:
            self.starter._post(
                "/realtime/hlc/roll",
                {"table": self.table, "server": self.starter.name,
                 "idx": self.idx, "seq": self.seq},
            )
        except Exception as e:
            # routing misses the new consuming segment until the
            # validation/repair tick re-registers it; data is safe
            logger.warning("HLC roll notify failed for %s: %s", self.segment, e)
        logger.info("HLC sealed %s (%d rows), rolled to %s", old, committed.num_docs, self.segment)
        return True


class NetworkedServerStarter:
    def __init__(
        self,
        controller_url: str,
        name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        data_dir: Optional[str] = None,
        heartbeat_interval_s: float = 1.0,
        poll_interval_s: float = 0.3,
        admin_port: int = 0,
        fault_injector=None,
    ) -> None:
        self.controller_url = controller_url.rstrip("/")
        self.name = name
        self.server = ServerInstance(name)
        self.tcp = TcpServer(self.server.handle_request, host=host, port=port)
        # ops/scrape surface: /health, /metrics (Prometheus), /debug/metrics
        self.admin = ServerAdminHttpServer(self.server, host=host, port=admin_port)
        self.data_dir = data_dir
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        # link-level chaos hook: every controller-bound HTTP call routes
        # through the injector as link (name -> "controller"), so a chaos
        # harness can cut/delay/duplicate this server's control plane
        self.fault_injector = fault_injector
        # partition-riding backoffs (full jitter, utils/retry.py): the
        # heartbeat and message loops keep their cadence while healthy
        # and back off jittered while the controller is unreachable, so
        # a healing controller is not hammered by the fleet in lockstep.
        # The HEARTBEAT backoff is capped below the controller's
        # advertised liveness timeout (tightened from the register
        # reply): under an ASYMMETRIC partition our requests still
        # arrive even though replies are lost, and backing off past the
        # timeout would flap this live server dead at the controller.
        from pinot_tpu.utils.retry import FullJitterBackoff

        self._hb_backoff = FullJitterBackoff(
            initial_s=max(0.1, heartbeat_interval_s), cap_s=2.0
        )
        # per-request timeout for heartbeat-loop RPCs, tightened with
        # the backoff cap (_tighten_hb_backoff) so a blackholed request
        # fails well before the liveness window elapses
        self._hb_timeout_s = 10.0
        self._msg_backoff = FullJitterBackoff(
            initial_s=max(0.1, poll_interval_s), cap_s=10.0
        )
        self._local_crcs: Dict[str, int] = {}
        self._consumers: Dict[str, RemoteConsumer] = {}  # segment -> consumer
        # partition-parallel ingest plane (realtime/pool.py): ONE
        # bounded worker pool drives every LLC consumer on this server
        # (PINOT_TPU_INGEST_CONSUMERS workers), so 100+ consuming
        # tables cost a fixed thread budget and N hot partitions
        # consume concurrently
        from pinot_tpu.realtime.pool import IngestConsumerPool

        self.ingest_pool = IngestConsumerPool(
            metrics=self.server.metrics, name=name
        )
        self._stop = threading.Event()
        # cross-signal wake: a heartbeat SUCCEEDING while the message
        # poll is deep in backoff means the controller is reachable
        # again — poll now instead of sleeping out the backoff window
        # (bounds recovery time: pending ONLINE transitions re-ack fast)
        self._msg_wake = threading.Event()
        self._threads: list = []
        # fleet plan prewarming (server/prewarm.py): the worker pulls
        # the controller's merged top-K workload for the tables this
        # server hosts; segment loads (ONLINE transitions) trigger the
        # passes, and the warming flag rides every heartbeat so the
        # controller can gate rebalance trims and tell the brokers
        self.server.prewarm.workload_source = self._fetch_workload

    # -- HTTP helpers --------------------------------------------------
    def _link(self, fn):
        """Run one controller-bound RPC through the link injector."""
        from pinot_tpu.common.faults import call_on_controller_link

        return call_on_controller_link(
            self.fault_injector, self.name, fn, metrics=self.server.metrics
        )

    def upload_segment_bytes(self, path: str, segment) -> Dict[str, Any]:
        """Serialize a committed segment and POST it to the controller
        (shared by the LLC committer and HLC seal paths)."""
        import tempfile

        from pinot_tpu.segment.format import write_segment

        with tempfile.TemporaryDirectory() as td:
            write_segment(segment, td)
            with open(os.path.join(td, SEGMENT_FILE_NAME), "rb") as f:
                data = f.read()

        def send():
            req = urllib.request.Request(
                self.controller_url + path,
                data=data,
                headers={"Content-Type": "application/octet-stream"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                return json.loads(r.read())

        return self._link(send)

    def _post(
        self, path: str, payload: Dict[str, Any], timeout_s: float = 10.0
    ) -> Dict[str, Any]:
        def send():
            req = urllib.request.Request(
                self.controller_url + path,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read())

        return self._link(send)

    def _get(self, path: str) -> Dict[str, Any]:
        def send():
            with urllib.request.urlopen(self.controller_url + path, timeout=10) as r:
                return json.loads(r.read())

        return self._link(send)

    def _fetch_workload(self, tables, n) -> list:
        """Prewarm workload feed: the controller's fleet-merged top-K
        plan shapes, narrowed to the given tables."""
        import urllib.parse

        qs = f"?n={int(n)}"
        if tables:
            qs += "&tables=" + urllib.parse.quote(",".join(tables))
        out = self._get("/debug/workload" + qs)
        return out.get("topByCount") or out.get("top") or []

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        # Initialize the jax backend on the MAIN thread before any
        # query can arrive: the accelerator plugin may fail to register
        # when its first initialization happens inside a scheduler
        # worker thread ("Backend 'axon' is not in the list of known
        # backends", observed on-chip).  Probed in a subprocess first so
        # a wedged device tunnel degrades to lazy init instead of
        # hanging server startup.
        from pinot_tpu.utils.platform import probe_device

        if os.environ.get("JAX_PLATFORMS") == "cpu" or probe_device(60.0):
            import jax

            jax.devices()
        else:
            logger.warning(
                "device backend probe failed; backend will initialize "
                "lazily on the first query"
            )
        self.tcp.start()
        self.admin.start()
        out = self._post(
            "/instances",
            {
                "name": self.name,
                "role": "server",
                "addr": [self.tcp.address[0], self.tcp.address[1]],
                # admin URL rides the registration so the controller
                # dashboard can aggregate this server's /debug/metrics
                "url": self.admin.url,
            },
        )
        # first serving lease rides the registration reply
        self.server.lease.renew(out.get("lease"))
        self._tighten_hb_backoff(out)
        for fn in (self._heartbeat_loop, self._message_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._msg_wake.set()  # unblock a message loop deep in backoff
        for consumer in list(self._consumers.values()):
            consumer.stop()
        self.ingest_pool.stop()
        for t in self._threads:
            t.join(timeout=2)
        self.tcp.stop()
        self.admin.stop()

    def _tighten_hb_backoff(self, reply: Dict[str, Any]) -> None:
        """Keep the worst-case heartbeat gap under the controller's
        liveness timeout (see __init__ on asymmetric partitions): the
        backoff cap AND the per-request timeout each take a third of
        the advertised window — a blackholed request blocking for the
        default 10s would exceed the 6s default window on its own."""
        timeout = reply.get("heartbeatTimeoutSeconds")
        if timeout:
            from pinot_tpu.utils.retry import tighten_liveness_budget

            self._hb_timeout_s = tighten_liveness_budget(
                self._hb_backoff, float(timeout), self._hb_timeout_s
            )

    def _heartbeat_loop(self) -> None:
        wait_s = self.heartbeat_interval_s
        unreachable = self.server.metrics.gauge("controller.unreachable")
        while not self._stop.wait(wait_s):
            try:
                out = self._post(
                    f"/instances/{self.name}/heartbeat",
                    # warm-start readiness rides the liveness beat: the
                    # controller folds it into the cluster state (broker
                    # deprioritization) and the rebalancer's trim gate
                    {"warming": bool(self.server.prewarm.warming)},
                    timeout_s=self._hb_timeout_s,
                )
                # drain ack: the controller tells us (on the heartbeat it
                # already makes) that an operator is draining this host;
                # surfaced in status() so ops tooling sees the ack
                self.server.draining = bool(out.get("draining"))
                # serving-lease renewal rides the same reply: write
                # authority extends lease_s from NOW.  A "held" reply
                # (flap hysteresis) carries no lease — correctly so.
                self.server.lease.renew(out.get("lease"))
                if out.get("reregister"):
                    reg = self._post(
                        "/instances",
                        {
                            "name": self.name,
                            "role": "server",
                            "addr": [self.tcp.address[0], self.tcp.address[1]],
                            "url": self.admin.url,
                        },
                        timeout_s=self._hb_timeout_s,
                    )
                    self.server.lease.renew(reg.get("lease"))
                if self._hb_backoff.failures:
                    # controller back after an outage: wake the message
                    # loop out of its backoff so queued transitions
                    # (e.g. pending ONLINE re-acks) land immediately,
                    # and kick frozen consumers out of their backoff
                    # parks (their next protocol round will now land)
                    self._msg_backoff.reset()
                    self._msg_wake.set()
                    self.ingest_pool.kick()
                self._hb_backoff.reset()
                unreachable.set(0)
                wait_s = self.heartbeat_interval_s
            except Exception as e:
                # partitioned from the controller: ride it out — serve
                # from local state, let the lease run down (write
                # authority self-fences), and retry with full jitter so
                # the fleet doesn't stampede the healing controller
                self.server.metrics.meter("controller.heartbeatFailures").mark()
                unreachable.set(1)
                wait_s = self._hb_backoff.next_delay()
                logger.warning(
                    "heartbeat to controller failed (%d consecutive, "
                    "retry in %.2fs): %s", self._hb_backoff.failures, wait_s, e,
                )

    def _message_loop(self) -> None:
        wait_s = self.poll_interval_s
        while True:
            if self._msg_wake.wait(timeout=wait_s):
                self._msg_wake.clear()
                wait_s = self.poll_interval_s
            if self._stop.is_set():
                return
            try:
                msgs = self._get(f"/instances/{self.name}/messages")["messages"]
                self._msg_backoff.reset()
                wait_s = self.poll_interval_s
            except Exception as e:
                wait_s = self._msg_backoff.next_delay()
                logger.warning(
                    "message poll failed (retry in %.2fs): %s", wait_s, e
                )
                continue
            for msg in msgs:
                self._handle(msg)

    # -- transitions ---------------------------------------------------
    def _handle(self, msg: Dict[str, Any]) -> None:
        table, segment, target = msg["table"], msg["segment"], msg["target"]
        if target == CONSUMING and not self.server.lease.held():
            # lease fence on WRITE authority: a server that cannot renew
            # its lease must not take on NEW consuming roles (another
            # replica may already own this partition as far as the live
            # controller is concerned).  Don't ack: the at-least-once
            # board redelivers once the lease renews.
            self.server.metrics.meter("lease.blockedTransitions").mark()
            logger.warning(
                "deferring CONSUMING %s/%s: serving lease expired",
                table, segment,
            )
            return
        try:
            if target == ONLINE:
                # CONSUMING -> ONLINE: retire the consumer before the
                # committed immutable copy replaces the mutable.  An HLC
                # consumer rolls itself to the next sequence (it may
                # still be keyed under the sealed name for an instant) —
                # never stop it here.
                consumer = self._consumers.get(segment)
                if consumer is not None and not getattr(consumer, "rolls_locally", False):
                    self._consumers.pop(segment, None)
                    consumer.stop()
                    self.ingest_pool.remove(segment)
                ok = self._load(
                    table,
                    segment,
                    msg.get("crc"),
                    msg.get("downloadUri"),
                    msg.get("invertedIndexColumns"),
                    msg.get("schemaJson"),
                )
            elif target == CONSUMING:
                ok = self._start_consumer(table, segment, msg)
            elif target in (OFFLINE, DROPPED):
                consumer = self._consumers.pop(segment, None)
                if consumer is not None:
                    consumer.stop()
                    self.ingest_pool.remove(segment)
                self.server.remove_segment(table, segment)
                self._local_crcs.pop(segment, None)
                ok = True
            else:
                logger.error("unsupported transition target %s", target)
                ok = False
        except Exception:
            logger.exception("transition %s/%s -> %s failed", table, segment, target)
            ok = False
        try:
            self._post(
                f"/instances/{self.name}/ack",
                {
                    "msgId": msg.get("msgId"),
                    "table": table,
                    "segment": segment,
                    "state": target,
                    "ok": ok,
                },
            )
        except Exception as e:
            # the un-acked message stays on the board and is redelivered
            logger.warning("ack failed for %s/%s: %s", table, segment, e)

    def _start_consumer(self, table: str, segment: str, msg: Dict[str, Any]) -> bool:
        if segment in self._consumers:
            return True  # redelivered message; don't reset the offset
        if not msg.get("streamDescriptor") or not msg.get("schemaJson"):
            logger.error("CONSUMING message for %s lacks a consume spec", segment)
            return False
        if msg.get("consumerType") == "highlevel":
            # one group member per (server, table): a replayed CONSUMING
            # for an older sequence (e.g. after controller recovery)
            # must not start a second consumer under the same member id
            for c in self._consumers.values():
                if getattr(c, "rolls_locally", False) and c.table == table:
                    return True
            consumer = HLRemoteConsumer(self, table, segment, msg)
        else:
            consumer = RemoteConsumer(self, table, segment, msg)
        self._consumers[segment] = consumer
        consumer.start()
        return True

    def _local_dir(self, table: str, segment: str) -> Optional[str]:
        if self.data_dir is None:
            return None
        return os.path.join(self.data_dir, table, segment)

    def _load(
        self,
        table: str,
        segment: str,
        crc: Optional[int],
        download_uri: Optional[str] = None,
        inv_columns=None,
        schema_json=None,
    ) -> bool:
        if schema_json is not None:
            self.server.set_table_schema(table, Schema.from_json(schema_json))
        tdm = self.server.data_manager.table(table)
        loaded = tdm is not None and segment in tdm.segment_names()
        if loaded and crc is not None and self._local_crcs.get(segment) == crc:
            return True  # CRC match (SegmentFetcherAndLoader.java:84)

        local = self._local_dir(table, segment)
        seg_obj = None
        if local is not None and os.path.exists(os.path.join(local, SEGMENT_FILE_NAME)):
            try:
                cached = read_segment(local)
                if crc is None or cached.metadata.crc == crc:
                    # local cache hit — but only a copy whose BYTES
                    # verify may serve (a bit-rotted cache with an
                    # intact header would otherwise sail through)
                    verify_segment_crc(cached, source=local)
                    seg_obj = cached
            except SegmentIntegrityError:
                # quarantine the corrupt cache copy aside (forensics)
                # and fall through to a verified re-download from the
                # controller's durable copy
                from pinot_tpu.server.starter import quarantine_local_copy

                self.server.record_crc_failure(table, segment)
                quarantine_local_copy(self.server, table, segment, local)
                logger.warning(
                    "corrupt local cache for %s/%s quarantined; re-downloading",
                    table, segment,
                )
            except Exception:
                logger.warning("corrupt local cache for %s/%s; re-downloading", table, segment)
        if seg_obj is None:
            # scheme-dispatched fetch (SegmentFetcherFactory.java):
            # an explicit downloadUri (hdfs://, external http…) wins;
            # default is the controller-served copy over HTTP.  With a
            # known CRC the factory verifies before install and returns
            # the parsed segment (no second decode); with crc=None the
            # download's own dataCrc claim is still self-verified — a
            # corrupt controller copy must never enter serving.
            from pinot_tpu.segment.fetcher import DEFAULT_FACTORY

            uri = download_uri or (
                f"{self.controller_url}/segments/{table}/{segment}/file"
            )
            try:
                if local is not None:
                    os.makedirs(local, exist_ok=True)
                    seg_obj = DEFAULT_FACTORY.fetch(
                        uri, os.path.join(local, SEGMENT_FILE_NAME), expected_crc=crc
                    )
                    if seg_obj is None:
                        seg_obj = read_segment(local)
                        verify_segment_crc(seg_obj, source=uri)
                else:
                    import tempfile

                    with tempfile.TemporaryDirectory() as td:
                        seg_obj = DEFAULT_FACTORY.fetch(
                            uri, os.path.join(td, SEGMENT_FILE_NAME), expected_crc=crc
                        )
                        if seg_obj is None:
                            seg_obj = read_segment(td)
                            verify_segment_crc(seg_obj, source=uri)
            except SegmentStaleError:
                # wrong VERSION at the source (replication lag), not
                # corruption: no counters, retried on the next transition
                logger.warning(
                    "controller copy of %s/%s is a stale version; leaving "
                    "unserved until it catches up", table, segment,
                )
                return False
            except SegmentIntegrityError:
                self.server.record_crc_failure(table, segment)
                # the DOWNLOADED bytes are bad: the store copy is the
                # suspect — report it so the controller's scrubber can
                # repair it from a healthy replica (reverse replication)
                try:
                    self._post(
                        "/deepstore/suspect",
                        {"table": table, "segment": segment, "source": uri},
                    )
                except Exception:
                    logger.warning(
                        "could not report store suspect %s/%s", table, segment
                    )
                logger.exception(
                    "downloaded copy of %s/%s failed integrity verification; "
                    "leaving unserved", table, segment,
                )
                return False
        self.server.add_segment(table, seg_obj)
        from pinot_tpu.segment.invindex import warm_inverted_indexes

        warm_inverted_indexes(seg_obj, inv_columns)
        if crc is not None:
            self._local_crcs[segment] = crc
        return True
