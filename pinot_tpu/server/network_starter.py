"""Networked server starter: a server process joining a remote controller.

The in-process ``ServerStarter`` receives transitions as direct
callbacks; this variant is the real-deployment analog of
``HelixServerStarter.java:63`` + ``SegmentFetcherAndLoader.java:84``:

- register with the controller over HTTP (PARTICIPANT join),
- heartbeat for liveness (the ZK session),
- poll transition messages, execute them (download segment bytes from
  the controller's store with CRC skip, load into the query engine, or
  drop), ack the resulting state,
- serve broker queries on a length-framed TCP socket.

All state the controller needs rides in the register/ack payloads; the
server keeps a local segment cache under ``data_dir`` so a restart with
matching CRCs skips downloads.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import urllib.request
from typing import Any, Dict, Optional

from pinot_tpu.controller.resource_manager import DROPPED, OFFLINE, ONLINE
from pinot_tpu.segment.format import SEGMENT_FILE_NAME, read_segment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.transport.tcp import TcpServer

logger = logging.getLogger(__name__)


class NetworkedServerStarter:
    def __init__(
        self,
        controller_url: str,
        name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        data_dir: Optional[str] = None,
        heartbeat_interval_s: float = 1.0,
        poll_interval_s: float = 0.3,
    ) -> None:
        self.controller_url = controller_url.rstrip("/")
        self.name = name
        self.server = ServerInstance(name)
        self.tcp = TcpServer(self.server.handle_request, host=host, port=port)
        self.data_dir = data_dir
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self._local_crcs: Dict[str, int] = {}
        self._stop = threading.Event()
        self._threads: list = []

    # -- HTTP helpers --------------------------------------------------
    def _post(self, path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.controller_url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def _get(self, path: str) -> Dict[str, Any]:
        with urllib.request.urlopen(self.controller_url + path, timeout=10) as r:
            return json.loads(r.read())

    def _download(self, path: str) -> bytes:
        with urllib.request.urlopen(self.controller_url + path, timeout=120) as r:
            return r.read()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self.tcp.start()
        self._post(
            "/instances",
            {
                "name": self.name,
                "role": "server",
                "addr": [self.tcp.address[0], self.tcp.address[1]],
            },
        )
        for fn in (self._heartbeat_loop, self._message_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self.tcp.stop()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                out = self._post(f"/instances/{self.name}/heartbeat", {})
                if out.get("reregister"):
                    self._post(
                        "/instances",
                        {
                            "name": self.name,
                            "role": "server",
                            "addr": [self.tcp.address[0], self.tcp.address[1]],
                        },
                    )
            except Exception as e:
                logger.warning("heartbeat to controller failed: %s", e)

    def _message_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                msgs = self._get(f"/instances/{self.name}/messages")["messages"]
            except Exception as e:
                logger.warning("message poll failed: %s", e)
                continue
            for msg in msgs:
                self._handle(msg)

    # -- transitions ---------------------------------------------------
    def _handle(self, msg: Dict[str, Any]) -> None:
        table, segment, target = msg["table"], msg["segment"], msg["target"]
        try:
            if target == ONLINE:
                ok = self._load(table, segment, msg.get("crc"))
            elif target in (OFFLINE, DROPPED):
                self.server.remove_segment(table, segment)
                self._local_crcs.pop(segment, None)
                ok = True
            else:
                logger.error("unsupported transition target %s", target)
                ok = False
        except Exception:
            logger.exception("transition %s/%s -> %s failed", table, segment, target)
            ok = False
        try:
            self._post(
                f"/instances/{self.name}/ack",
                {
                    "msgId": msg.get("msgId"),
                    "table": table,
                    "segment": segment,
                    "state": target,
                    "ok": ok,
                },
            )
        except Exception as e:
            # the un-acked message stays on the board and is redelivered
            logger.warning("ack failed for %s/%s: %s", table, segment, e)

    def _local_dir(self, table: str, segment: str) -> Optional[str]:
        if self.data_dir is None:
            return None
        return os.path.join(self.data_dir, table, segment)

    def _load(self, table: str, segment: str, crc: Optional[int]) -> bool:
        tdm = self.server.data_manager.table(table)
        loaded = tdm is not None and segment in tdm.segment_names()
        if loaded and crc is not None and self._local_crcs.get(segment) == crc:
            return True  # CRC match (SegmentFetcherAndLoader.java:84)

        local = self._local_dir(table, segment)
        seg_obj = None
        if local is not None and os.path.exists(os.path.join(local, SEGMENT_FILE_NAME)):
            try:
                cached = read_segment(local)
                if crc is None or cached.metadata.crc == crc:
                    seg_obj = cached  # local cache hit, skip download
            except Exception:
                logger.warning("corrupt local cache for %s/%s; re-downloading", table, segment)
        if seg_obj is None:
            data = self._download(f"/segments/{table}/{segment}/file")
            if local is not None:
                os.makedirs(local, exist_ok=True)
                with open(os.path.join(local, SEGMENT_FILE_NAME), "wb") as f:
                    f.write(data)
                seg_obj = read_segment(local)
            else:
                import tempfile

                with tempfile.TemporaryDirectory() as td:
                    p = os.path.join(td, SEGMENT_FILE_NAME)
                    with open(p, "wb") as f:
                        f.write(data)
                    seg_obj = read_segment(td)
        self.server.add_segment(table, seg_obj)
        if crc is not None:
            self._local_crcs[segment] = crc
        return True
