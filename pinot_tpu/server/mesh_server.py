"""Multi-host serving topology: broker PQL answered by a (hosts, chips)
mesh (VERDICT r3 #7 — the single-program ICI+DCN path wired into the
serving stack, not just the SPMD harness).

The reference scales serving across machines only by scatter-gather
over TCP (``ScatterGatherImpl.java:80``): every server computes its own
partial and the broker merges.  A TPU pod slice offers a second,
stronger topology: all hosts of the slice run ONE sharded program, XLA
merges partials over ICI within a host and DCN across hosts, and the
broker talks to a single endpoint.  This module is that server mode:

- every host process builds the global (hosts, chips) mesh via
  ``jax.distributed`` (``parallel/multihost.py``) and owns the SAME
  table/segment view (each device holds its shard of the stacked
  segment axis — XLA partitions the arrays, so per-host HBM holds only
  its slice);
- the LEAD host (process 0) serves the framework's length-framed
  query protocol to brokers, so it drops into ``BrokerRequestHandler``
  routing like any scatter-gather server;
- because the program is SPMD, every process must enter the kernel for
  its collectives to complete: the lead forwards each InstanceRequest
  to the followers over the data-plane TCP transport *before* running
  it locally, and a per-process FIFO (one in-flight query, matching
  arrival order) keeps collective ordering identical everywhere —
  jax.distributed requires identical program order across processes.

The lead's reply alone carries the answer (psum leaves the reduced
value on every process; the followers' copies are dropped), so the
broker sees an ordinary single-server response with the whole mesh's
throughput behind it.
"""
from __future__ import annotations

import concurrent.futures
import logging
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.transport.tcp import TcpServer, TcpTransport

logger = logging.getLogger(__name__)


class MultihostQueryServer:
    """One host process of a mesh-serving group.

    Call :meth:`connect_followers` on the lead (process 0) once every
    follower's TCP address is known; then point a broker at
    ``lead.address``.
    """

    def __init__(
        self,
        table: str,
        segments: Sequence[ImmutableSegment],
        coordinator_address: Optional[str],
        num_processes: int,
        process_id: int,
        name: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        from pinot_tpu.parallel.multihost import (
            flatten_to_segment_mesh,
            initialize_distributed,
            make_multihost_mesh,
        )

        initialize_distributed(coordinator_address, num_processes, process_id)
        mesh = flatten_to_segment_mesh(make_multihost_mesh())
        self.process_id = process_id
        self.is_lead = process_id == 0
        self.name = name or f"meshhost{process_id}"
        # num_workers=1: queries execute strictly in arrival order —
        # the SPMD contract (identical collective order on every
        # process) forbids concurrent kernels
        self.server = ServerInstance(self.name, mesh=mesh, num_workers=1)
        for seg in segments:
            self.server.add_segment(table, seg)
        self._followers: List[Tuple[str, int]] = []
        self._transport = TcpTransport()
        self._fanout = ThreadPoolExecutor(max_workers=8)
        self._order_lock = threading.Lock()
        # set when a follower failed AFTER the query was forwarded: the
        # collective program order across processes is no longer
        # trustworthy (survivors may be wedged in a psum barrier) and
        # jax.distributed cannot re-admit a restarted process — the
        # recovery contract is an immediate typed error on every
        # subsequent query until the serving group is restarted
        self.degraded: Optional[str] = None
        self.tcp = TcpServer(self._handle, host=host, port=port)
        self.tcp.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self.tcp.address

    def connect_followers(self, addresses: Sequence[Tuple[str, int]]) -> None:
        self._followers = [tuple(a) for a in addresses]

    PING = b"\x00MESHPING"
    PONG = b"\x00MESHPONG"

    def _error_reply(self, msg: str) -> bytes:
        from pinot_tpu.common.datatable import serialize_result
        from pinot_tpu.common.response import ErrorCode
        from pinot_tpu.engine.results import IntermediateResult

        logger.error("%s", msg)
        return serialize_result(
            IntermediateResult(exceptions=[(ErrorCode.QUERY_EXECUTION, msg)])
        )

    # -- query path ----------------------------------------------------
    def _handle(self, payload: bytes) -> bytes:
        if payload == self.PING:
            return self.PONG
        if self.degraded is not None:
            return self._error_reply(
                f"mesh serving group degraded ({self.degraded}); "
                "restart the group to re-form the jax.distributed mesh"
            )
        with self._order_lock:
            if self.degraded is not None:
                # a query blocked on the lock while the one ahead of it
                # degraded the group must NOT proceed into the dead
                # collective
                return self._error_reply(
                    f"mesh serving group degraded ({self.degraded}); "
                    "restart the group to re-form the jax.distributed mesh"
                )
            # Liveness preflight BEFORE forwarding anything: once any
            # follower holds the query it will enter the collective, so
            # discovering a dead peer after forwarding would wedge the
            # survivors in the psum barrier.  The short ping timeout
            # also catches network-partitioned hosts whose connects
            # hang rather than refuse.  A follower dying between ping
            # and kernel entry is left to jax.distributed's own
            # failure detection.
            ping_futs = [
                self._fanout.submit(self._transport.request, addr, self.PING, 5.0)
                for addr in self._followers
            ]
            down = []
            for addr, f in zip(self._followers, ping_futs):
                try:
                    if f.result(timeout=6.0) != self.PONG:
                        down.append((addr, "bad ping reply"))
                except Exception as e:
                    down.append((addr, e))
            if down:
                msg = "; ".join(f"{a}: {e}" for a, e in down)
                return self._error_reply(f"mesh followers unreachable: {msg}")
            # forward, then run locally (awaiting follower replies
            # before running would deadlock the collective)
            futures = [
                self._fanout.submit(self._transport.request, addr, payload, 600.0)
                for addr in self._followers
            ]
            # The hard failure window (r4 VERDICT #7): a follower dying
            # BETWEEN the preflight ping and collective entry.  Its
            # request future fails fast (connection reset / refused),
            # while a healthy follower's future stays pending until it
            # finishes executing — so a short grace watch that reacts
            # only to EXCEPTIONS distinguishes the two.  Aborting
            # before the lead enters the kernel keeps this process out
            # of the doomed psum barrier; the group is still marked
            # degraded because other followers may already be in it.
            # FIRST_EXCEPTION returns the moment a forward fails; the
            # healthy path always pays the full grace (followers cannot
            # reply before the lead runs its kernel), so the default is
            # a small fixed latency tax chosen against localhost/ICI
            # connect-failure times — tune per deployment via env.
            try:
                grace = float(os.environ.get("PINOT_TPU_MESH_FORWARD_GRACE_S", "0.05"))
            except ValueError:
                grace = 0.05
            done, _pending = concurrent.futures.wait(
                futures, timeout=grace,
                return_when=concurrent.futures.FIRST_EXCEPTION,
            )
            dead = [f.exception() for f in done if f.exception() is not None]
            if dead:
                self.degraded = f"follower died after forward: {dead[0]}"
                return self._error_reply(
                    f"mesh follower failed between preflight and collective "
                    f"entry: {dead[0]}; group requires restart"
                )
            reply = self.server.handle_request(payload)
            for f in futures:
                try:
                    f.result(timeout=600.0)
                except Exception as e:
                    logger.exception("follower fan-out failed")
                    # the local kernel came back (possibly via timeout)
                    # but a peer never completed: collective order is no
                    # longer consistent across processes
                    self.degraded = f"follower fan-out failed: {e}"
            return reply

    def stop(self) -> None:
        self.tcp.stop()
        self._fanout.shutdown(wait=False)
