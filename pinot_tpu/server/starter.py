"""Server starter: wires a ServerInstance into the cluster as a
participant.

The reference's ``HelixServerStarter.java:63`` registers a state-model
factory whose transitions download + load segments
(``SegmentFetcherAndLoader.java:84``: compare local CRC vs metadata CRC,
skip if equal, else fetch/untar/load).  Here the participant callback
loads from the controller's segment store path (or takes the in-memory
segment for freshly-committed realtime segments).

INTEGRITY: every disk load verifies the column-data CRC against the
metadata claim.  With a server-local ``data_dir`` the starter keeps its
own durable copy per segment (fetched from ``downloadUri`` — the
controller's store); a copy that fails verification is QUARANTINED
(directory renamed aside, segment pulled from serving, staged device
arrays evicted) and re-fetched from the controller copy, so local bit
rot costs one re-download, never a wrong answer.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Optional

from pinot_tpu.controller.resource_manager import (
    ClusterResourceManager,
    DROPPED,
    InstanceState,
    OFFLINE,
    ONLINE,
    CONSUMING,
    Participant,
)
from pinot_tpu.segment.format import (
    SEGMENT_FILE_NAME,
    SegmentIntegrityError,
    SegmentStaleError,
    read_segment,
    verify_segment_crc,
)
from pinot_tpu.server.instance import ServerInstance

logger = logging.getLogger(__name__)


class ServerStarter:
    def __init__(
        self,
        server: ServerInstance,
        resources: ClusterResourceManager,
        data_dir: Optional[str] = None,
        workload_source=None,
    ) -> None:
        self.server = server
        self.resources = resources
        # server-local segment cache; None = read the shared store path
        # directly (in-process clusters) — quarantine then only pulls
        # the segment from serving (we never rename a dir we don't own)
        self.data_dir = data_dir
        self._local_crcs: Dict[str, int] = {}  # segment -> crc loaded
        # fleet workload feed for the prewarm worker (server/prewarm.py):
        # in-process harnesses pass a closure over a broker's plan-stat
        # registry; segment loads below then trigger prewarm passes
        if workload_source is not None:
            server.prewarm.workload_source = workload_source

    def start(self) -> None:
        self.resources.register_instance(
            InstanceState(self.server.name, role="server"),
            Participant(self.server.name, self.on_transition),
        )
        # replay any ideal-state transitions already targeting this
        # instance (CRC-skip makes re-loads cheap) — this is what makes
        # a server joining a *recovered* controller reload its segments
        self.resources.reconcile_instance(self.server.name)

    def on_transition(
        self, table: str, segment: str, target: str, info: Dict[str, Any]
    ) -> bool:
        if target == ONLINE:
            return self._load(table, segment, info)
        if target == CONSUMING:
            starter = info.get("consuming_starter")
            if starter is None:
                return False
            return bool(starter(self.server, table, segment, info))
        if target in (OFFLINE, DROPPED):
            self.server.remove_segment(table, segment)
            self._local_crcs.pop(segment, None)
            return True
        return False

    def _load(self, table: str, segment: str, info: Dict[str, Any]) -> bool:
        meta = info.get("metadata")
        crc = meta.crc if meta is not None else None
        # schema applies even on the CRC-skip path: a reload broadcast
        # after schema evolution must patch already-loaded segments with
        # default columns without re-reading any bytes
        schema = info.get("schema")
        if schema is not None:
            self.server.set_table_schema(table, schema)
        tdm = self.server.data_manager.table(table)
        actually_loaded = tdm is not None and segment in tdm.segment_names()
        if actually_loaded and crc is not None and self._local_crcs.get(segment) == crc:
            return True  # CRC match: already loaded (SegmentFetcherAndLoader.java:84)
        seg_obj = info.get("segment")  # in-memory handoff (realtime commit)
        if seg_obj is None:
            # disk loads verify inside _load_from_store (quarantine +
            # re-fetch live there); in-memory handoffs were built in this
            # process and are trusted — a consuming snapshot's crc field
            # is a watermark identity hash, not a data CRC
            seg_obj = self._load_from_store(table, segment, info, crc)
            if seg_obj is None:
                return False
        self.server.add_segment(table, seg_obj)
        from pinot_tpu.segment.invindex import warm_inverted_indexes

        warm_inverted_indexes(seg_obj, info.get("invertedIndexColumns"))
        if crc is not None:
            self._local_crcs[segment] = crc
        return True

    # -- disk load + integrity quarantine ------------------------------
    def _local_segment_dir(self, table: str, segment: str) -> str:
        return os.path.join(self.data_dir, table, segment)

    def _report_store_suspect(self, table: str, segment: str, uri: str) -> None:
        """Feed the controller's DeepStoreScrubber: the STORE copy
        served bytes that failed CRC, so the store side — not just the
        local copy — is suspect and due for reverse replication."""
        cb = getattr(self.resources, "report_store_suspect", None)
        if cb is None:
            return
        try:
            cb(table, segment, uri or "")
        except Exception:
            logger.exception(
                "failed to report store suspect %s/%s", table, segment
            )

    def _load_from_store(
        self, table: str, segment: str, info: Dict[str, Any], crc: Optional[int]
    ) -> Optional["object"]:
        path = info.get("dir")
        uri = info.get("downloadUri")
        if path is None and uri is None:
            logger.error("segment %s/%s has no download info", table, segment)
            return None
        if self.data_dir is not None and uri is not None:
            return self._load_via_local_copy(table, segment, uri, crc)
        try:
            if path is not None:
                seg_obj = read_segment(path)
                verify_segment_crc(seg_obj, source=path)
            else:
                # scheme-dispatched fetch (SegmentFetcherFactory.java),
                # CRC-verified before the temp copy is even loaded; the
                # self-verify after read also covers crc=None messages
                # (the download's own dataCrc claim must still hold)
                import tempfile

                from pinot_tpu.segment.fetcher import DEFAULT_FACTORY

                with tempfile.TemporaryDirectory() as td:
                    seg_obj = DEFAULT_FACTORY.fetch(
                        uri,
                        os.path.join(td, SEGMENT_FILE_NAME),
                        expected_crc=crc,
                        suspect_cb=lambda u, e: self._report_store_suspect(
                            table, segment, u
                        ),
                    )
                    if seg_obj is None:  # crc unknown: self-verify claim
                        seg_obj = read_segment(td)
                        verify_segment_crc(seg_obj, source=uri)
            return seg_obj
        except SegmentIntegrityError:
            # a corrupt SHARED copy is the controller's to fix; pull the
            # segment from serving and report, but never rename a
            # directory this server does not own
            self.server.record_crc_failure(table, segment)
            self.server.quarantine_segment(table, segment)
            self._report_store_suspect(table, segment, uri or path or "")
            logger.exception(
                "segment %s/%s failed integrity verification at %s",
                table, segment, path or uri,
            )
            return None
        except Exception:
            logger.exception(
                "failed to load %s/%s from %s", table, segment, path or uri
            )
            return None

    def _load_via_local_copy(
        self, table: str, segment: str, uri: str, crc: Optional[int]
    ) -> Optional["object"]:
        """Load from the server-local copy, (re-)fetching from the
        controller's durable copy as needed.  One quarantine + re-fetch
        round heals local corruption; a second failure means the SOURCE
        is bad and the segment stays out of serving (the broker's
        partialResponse contract covers it meanwhile)."""
        d = self._local_segment_dir(table, segment)
        fpath = os.path.join(d, SEGMENT_FILE_NAME)
        from pinot_tpu.segment.fetcher import DEFAULT_FACTORY

        for attempt in (0, 1):
            try:
                if not os.path.exists(fpath):
                    os.makedirs(d, exist_ok=True)
                    # the factory returns the parsed + verified segment:
                    # no second decode/CRC pass over a multi-GB file
                    fetched = DEFAULT_FACTORY.fetch(
                        uri,
                        fpath,
                        expected_crc=crc,
                        suspect_cb=lambda u, e: self._report_store_suspect(
                            table, segment, u
                        ),
                    )
                    if fetched is not None:
                        return fetched
                seg_obj = read_segment(d)
                if crc is not None and seg_obj.metadata.crc and seg_obj.metadata.crc != crc:
                    # STALE, not corrupt: the ideal state moved to a new
                    # CRC (routine segment refresh) — replace the intact
                    # old copy silently, no quarantine, no counters
                    logger.info(
                        "segment %s/%s: local copy CRC %s behind ideal-state"
                        " %s; re-downloading", table, segment,
                        seg_obj.metadata.crc, crc,
                    )
                    try:
                        os.remove(fpath)
                    except OSError:
                        pass
                    if attempt:
                        return None
                    continue
                verify_segment_crc(seg_obj, source=fpath)
                return seg_obj
            except SegmentStaleError:
                # the SOURCE copy is a different version than the ideal
                # state asked for (replication lag): no quarantine, no
                # corruption counters — retried on the next transition
                logger.warning(
                    "segment %s/%s: controller copy at %s is a stale "
                    "version; leaving unserved until it catches up",
                    table, segment, uri,
                )
                return None
            except SegmentIntegrityError:
                self.server.record_crc_failure(table, segment)
                quarantine_local_copy(self.server, table, segment, d)
                if attempt:
                    logger.exception(
                        "segment %s/%s corrupt after re-fetch from %s; "
                        "leaving unserved", table, segment, uri,
                    )
                    return None
                logger.warning(
                    "segment %s/%s: local copy corrupt; quarantined, "
                    "re-fetching from %s", table, segment, uri,
                )
            except Exception:
                logger.exception(
                    "failed to load %s/%s from %s", table, segment, uri
                )
                return None
        return None


def quarantine_local_copy(
    server: ServerInstance, table: str, segment: str, d: str
) -> None:
    """Shared quarantine step for server-local segment copies (used by
    both the in-process and the networked starter): move the corrupt
    copy aside (kept for forensics, out of every load path) and pull the
    segment from serving.  When there is no on-disk copy to impound (a
    verified fetch refused to land one), only the serving pull happens —
    no rename of an empty dir, no double-count of
    ``quarantinedSegments`` for the same incident."""
    if os.path.exists(os.path.join(d, SEGMENT_FILE_NAME)):
        server.quarantine_segment(table, segment)
        target = f"{d}.quarantined.{int(time.time() * 1000)}"
        try:
            os.rename(d, target)
        except OSError:
            logger.exception("could not quarantine %s", d)
    else:
        server.remove_segment(table, segment)
