"""Server starter: wires a ServerInstance into the cluster as a
participant.

The reference's ``HelixServerStarter.java:63`` registers a state-model
factory whose transitions download + load segments
(``SegmentFetcherAndLoader.java:84``: compare local CRC vs metadata CRC,
skip if equal, else fetch/untar/load).  Here the participant callback
loads from the controller's segment store path (or takes the in-memory
segment for freshly-committed realtime segments).
"""
from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from pinot_tpu.controller.resource_manager import (
    ClusterResourceManager,
    DROPPED,
    InstanceState,
    OFFLINE,
    ONLINE,
    CONSUMING,
    Participant,
)
from pinot_tpu.segment.format import read_segment
from pinot_tpu.server.instance import ServerInstance

logger = logging.getLogger(__name__)


class ServerStarter:
    def __init__(self, server: ServerInstance, resources: ClusterResourceManager) -> None:
        self.server = server
        self.resources = resources
        self._local_crcs: Dict[str, int] = {}  # segment -> crc loaded

    def start(self) -> None:
        self.resources.register_instance(
            InstanceState(self.server.name, role="server"),
            Participant(self.server.name, self.on_transition),
        )
        # replay any ideal-state transitions already targeting this
        # instance (CRC-skip makes re-loads cheap) — this is what makes
        # a server joining a *recovered* controller reload its segments
        self.resources.reconcile_instance(self.server.name)

    def on_transition(
        self, table: str, segment: str, target: str, info: Dict[str, Any]
    ) -> bool:
        if target == ONLINE:
            return self._load(table, segment, info)
        if target == CONSUMING:
            starter = info.get("consuming_starter")
            if starter is None:
                return False
            return bool(starter(self.server, table, segment, info))
        if target in (OFFLINE, DROPPED):
            self.server.remove_segment(table, segment)
            self._local_crcs.pop(segment, None)
            return True
        return False

    def _load(self, table: str, segment: str, info: Dict[str, Any]) -> bool:
        meta = info.get("metadata")
        crc = meta.crc if meta is not None else None
        # schema applies even on the CRC-skip path: a reload broadcast
        # after schema evolution must patch already-loaded segments with
        # default columns without re-reading any bytes
        schema = info.get("schema")
        if schema is not None:
            self.server.set_table_schema(table, schema)
        tdm = self.server.data_manager.table(table)
        actually_loaded = tdm is not None and segment in tdm.segment_names()
        if actually_loaded and crc is not None and self._local_crcs.get(segment) == crc:
            return True  # CRC match: already loaded (SegmentFetcherAndLoader.java:84)
        seg_obj = info.get("segment")  # in-memory handoff (realtime commit)
        if seg_obj is None:
            path = info.get("dir")
            uri = info.get("downloadUri")
            if path is None and uri is None:
                logger.error("segment %s/%s has no download info", table, segment)
                return False
            try:
                if path is not None:
                    seg_obj = read_segment(path)
                else:
                    # scheme-dispatched fetch (SegmentFetcherFactory.java)
                    import tempfile

                    from pinot_tpu.segment.fetcher import DEFAULT_FACTORY
                    from pinot_tpu.segment.format import SEGMENT_FILE_NAME

                    with tempfile.TemporaryDirectory() as td:
                        DEFAULT_FACTORY.fetch(uri, os.path.join(td, SEGMENT_FILE_NAME))
                        seg_obj = read_segment(td)
            except Exception:
                logger.exception(
                    "failed to load %s/%s from %s", table, segment, path or uri
                )
                return False
        self.server.add_segment(table, seg_obj)
        from pinot_tpu.segment.invindex import warm_inverted_indexes

        warm_inverted_indexes(seg_obj, info.get("invertedIndexColumns"))
        if crc is not None:
            self._local_crcs[segment] = crc
        return True
