"""On-demand deep profiling: bracketed ``jax.profiler`` trace capture.

The utilization plane's gauges (``device.util.*``) answer "HOW utilized
is the device"; when a lane saturates in production the next question
is "on WHAT" — and that needs an XLA/TensorBoard trace.  This module
makes capture an admin-endpoint action instead of a restart:

  POST /debug/profile/start   begin (or join) a capture
  POST /debug/profile/stop    release one start; capture ends at zero
  GET  /debug/profile         live state + capture directory listing

Semantics:

- **Ref-counted**: concurrent starts share ONE capture (jax allows a
  single active trace per process); each ``start`` must be paired with
  a ``stop``, and the trace stops when the count reaches zero.
- **Auto-stop timeout**: every start (re-)arms a deadline
  (``PINOT_TPU_PROFILE_AUTO_STOP_S``, default 120s); a client that
  dies mid-capture cannot leave the profiler running forever — the
  timer force-stops regardless of the count and marks
  ``profile.autoStops``.
- **Bounded on disk**: captures land under one base directory
  (``PINOT_TPU_PROFILE_DIR`` or a per-process tempdir), one
  subdirectory per capture, oldest pruned beyond ``max_captures``.
- **Typed unavailability**: a backend without a working profiler
  raises ``ProfilerUnavailableError``; the admin endpoint maps it to a
  404 with ``errorType`` so callers can distinguish "no profiler" from
  "bad request".

The hot path cost while idle is literally zero — nothing is consulted
per query; the profiler only acts inside start/stop.
"""
from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class ProfilerUnavailableError(RuntimeError):
    """jax.profiler missing or its trace backend refused to start."""


def _default_trace_api():
    try:
        from jax import profiler as jprof

        return jprof.start_trace, jprof.stop_trace
    except Exception as e:  # pragma: no cover - import environment
        raise ProfilerUnavailableError(f"jax.profiler unavailable: {e}")


class DeviceProfiler:
    """Ref-counted, auto-stopping ``jax.profiler`` capture manager.

    ``trace_api`` ((start_fn(dir), stop_fn()) tuple) and ``clock`` are
    injectable for unit tests; production uses ``jax.profiler`` and
    ``time.monotonic``."""

    def __init__(
        self,
        name: str = "server",
        base_dir: Optional[str] = None,
        metrics=None,
        auto_stop_s: Optional[float] = None,
        max_captures: int = 4,
        trace_api=None,
        clock=time.monotonic,
    ) -> None:
        if base_dir is None:
            base_dir = os.environ.get("PINOT_TPU_PROFILE_DIR")
        if base_dir is None:
            import tempfile

            base_dir = os.path.join(
                tempfile.gettempdir(), "pinot_tpu_profiles", f"{name}-{os.getpid()}"
            )
        self.base_dir = base_dir
        self.max_captures = max(1, max_captures)
        if auto_stop_s is None:
            auto_stop_s = float(
                os.environ.get("PINOT_TPU_PROFILE_AUTO_STOP_S", "120")
            )
        self.auto_stop_s = auto_stop_s
        self.metrics = metrics
        self._trace_api = trace_api
        self._clock = clock
        self._lock = threading.Lock()
        self._refcount = 0
        self._capture_dir: Optional[str] = None
        self._started_at: Optional[float] = None
        self._deadline: Optional[float] = None
        self._timer: Optional[threading.Timer] = None
        self._seq = 0
        # capture dirs are immutable once their trace stops, so their
        # sizes are computed once and cached — snapshot() sits on polled
        # paths (/debug/device, status()) and must not re-walk hundreds
        # of MB of trace files per scrape, let alone under self._lock
        self._size_cache: Dict[str, int] = {}
        self.auto_stops = 0
        # optional hook fired whenever a capture ends (stop or
        # auto-stop): the server uses it to park its occupancy sampler
        self.on_capture_end = None
        if metrics is not None:
            # pre-registered so /metrics shows zeros before first use
            for m in ("profile.starts", "profile.stops", "profile.autoStops",
                      "profile.failedStarts"):
                metrics.meter(m)
            metrics.gauge("profile.active").set(0)

    # -- public API ----------------------------------------------------
    def start(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Begin a capture, or join the active one (refcount++).  Every
        start re-arms the auto-stop deadline to now + timeout (capped
        callers extend; a second client cannot SHORTEN a running
        capture's remaining window below its own request)."""
        timeout = float(timeout_s) if timeout_s else self.auto_stop_s
        with self._lock:
            if self._refcount == 0:
                self._begin_capture_locked()
            self._refcount += 1
            now = self._clock()
            deadline = now + max(0.1, timeout)
            if self._deadline is None or deadline > self._deadline:
                self._deadline = deadline
                self._arm_timer_locked(self._deadline - now)
            if self.metrics is not None:
                self.metrics.meter("profile.starts").mark()
                self.metrics.gauge("profile.active").set(1)
            return self._snapshot_locked()

    def stop(self) -> Dict[str, Any]:
        """Release one start; the trace stops when the count hits zero.
        Stopping an inactive profiler is a no-op snapshot (idempotent
        — a retried stop after a timeout must not error)."""
        ended = False
        with self._lock:
            if self._refcount > 0:
                self._refcount -= 1
                if self.metrics is not None:
                    self.metrics.meter("profile.stops").mark()
                if self._refcount == 0:
                    ended = self._end_capture_locked()
            snap = self._snapshot_locked()
        if ended:
            self._fire_capture_end()
        return snap

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._snapshot_locked()

    def shutdown(self) -> None:
        """Force-stop any active capture (server shutdown path)."""
        ended = False
        with self._lock:
            if self._refcount > 0:
                self._refcount = 0
                ended = self._end_capture_locked()
        if ended:
            self._fire_capture_end()

    # -- internals -----------------------------------------------------
    def _begin_capture_locked(self) -> None:
        start_fn, _ = self._api()
        self._seq += 1
        capture_dir = os.path.join(
            self.base_dir, f"capture-{self._seq:04d}-{int(time.time())}"
        )
        try:
            # prune BEFORE creating the new dir: pruning after would
            # count the new capture among the victims-by-age candidates
            # (with max_captures=1 it would rmtree the dir the trace is
            # about to write into)
            self._prune_captures_locked(keep=self.max_captures - 1)
            os.makedirs(capture_dir, exist_ok=True)
            start_fn(capture_dir)
        except ProfilerUnavailableError:
            raise
        except Exception as e:
            if self.metrics is not None:
                self.metrics.meter("profile.failedStarts").mark()
            raise ProfilerUnavailableError(
                f"profiler trace failed to start: {type(e).__name__}: {e}"
            )
        self._capture_dir = capture_dir
        self._started_at = time.time()

    def _end_capture_locked(self) -> bool:
        """Returns True when an active capture actually ended — the
        caller fires ``on_capture_end`` AFTER releasing the lock (the
        hook may join the occupancy sampler thread for seconds, and a
        concurrent snapshot/start must not stall behind that)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._deadline = None
        if self._capture_dir is None:
            return False
        _, stop_fn = self._api()
        try:
            stop_fn()
        except Exception as e:
            # a capture that failed mid-flight must still reset state:
            # the NEXT start has to be able to begin a fresh trace
            logger.warning("profiler stop_trace failed: %s", e)
        self._capture_dir = None
        self._started_at = None
        if self.metrics is not None:
            self.metrics.gauge("profile.active").set(0)
        return True

    def _fire_capture_end(self) -> None:
        if self.on_capture_end is not None:
            try:
                self.on_capture_end()
            except Exception:
                logger.exception("profiler on_capture_end hook failed")

    def _api(self):
        if self._trace_api is not None:
            return self._trace_api
        return _default_trace_api()

    def _arm_timer_locked(self, delay_s: float) -> None:
        if self._timer is not None:
            self._timer.cancel()
        t = threading.Timer(max(0.05, delay_s), self._auto_stop)
        t.daemon = True
        self._timer = t
        t.start()

    def _auto_stop(self) -> None:
        """Deadline fired: force-stop REGARDLESS of refcount — a dead
        client's unmatched start must not pin the profiler open."""
        ended = False
        with self._lock:
            if self._capture_dir is None:
                return
            if self._deadline is not None and self._clock() < self._deadline - 1e-3:
                # a later start extended the deadline after this timer
                # was armed; re-arm for the remainder instead
                self._arm_timer_locked(self._deadline - self._clock())
                return
            self._refcount = 0
            self.auto_stops += 1
            if self.metrics is not None:
                self.metrics.meter("profile.autoStops").mark()
            ended = self._end_capture_locked()
        if ended:
            self._fire_capture_end()

    def _prune_captures_locked(self, keep: int) -> None:
        try:
            entries = sorted(
                d
                for d in os.listdir(self.base_dir)
                if d.startswith("capture-")
                and os.path.isdir(os.path.join(self.base_dir, d))
            )
        except OSError:
            return
        for victim in entries[: max(0, len(entries) - max(0, keep))]:
            shutil.rmtree(os.path.join(self.base_dir, victim), ignore_errors=True)

    def _dir_bytes(self, path: str) -> int:
        nbytes = 0
        for root, _, files in os.walk(path):
            for f in files:
                try:
                    nbytes += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return nbytes

    def _captures_locked(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            entries = sorted(
                d
                for d in os.listdir(self.base_dir)
                if d.startswith("capture-")
            )
        except OSError:
            return out
        live = set(entries)
        for stale in [k for k in self._size_cache if k not in live]:
            del self._size_cache[stale]
        for d in entries:
            path = os.path.join(self.base_dir, d)
            if path == self._capture_dir:
                # still being written: size unknown until the trace stops
                out.append({"name": d, "bytes": None})
                continue
            nbytes = self._size_cache.get(d)
            if nbytes is None:
                nbytes = self._dir_bytes(path)
                self._size_cache[d] = nbytes
            out.append({"name": d, "bytes": nbytes})
        return out

    def _snapshot_locked(self) -> Dict[str, Any]:
        now = self._clock()
        return {
            "active": self._capture_dir is not None,
            "refCount": self._refcount,
            "dir": self._capture_dir,
            "baseDir": self.base_dir,
            "startedAt": self._started_at,
            "autoStopS": self.auto_stop_s,
            "remainingS": (
                round(max(0.0, self._deadline - now), 3)
                if self._deadline is not None and self._capture_dir is not None
                else None
            ),
            "autoStops": self.auto_stops,
            "maxCaptures": self.max_captures,
            "captures": self._captures_locked(),
        }
