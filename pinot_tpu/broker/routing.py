"""Broker routing: external view -> precomputed routing tables.

The reference listens to Helix ExternalView changes and precomputes N
routing tables per table — each a full ``{server -> segment set}``
cover with one random ONLINE replica chosen per segment — then picks a
random table per query (``HelixExternalViewBasedRouting.java:65``,
``BalancedRandomRoutingTableBuilder.java``).  Same design here, fed by
the controller's external view (``pinot_tpu.controller``) or a static
map.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Set, Tuple

# external view shape: {segment_name: {server_name: state}}; state in
# ONLINE | CONSUMING | OFFLINE | ERROR
ExternalView = Dict[str, Dict[str, str]]
RoutingTable = Dict[str, List[str]]  # server -> segments

ONLINE_STATES = ("ONLINE", "CONSUMING")


def balanced_random_routing_tables(
    external_view: ExternalView, num_tables: int = 10, seed: int = 0
) -> List[RoutingTable]:
    """Precompute N random replica-balanced covers of all segments."""
    rng = random.Random(seed)
    out: List[RoutingTable] = []
    for _ in range(max(1, num_tables)):
        table: RoutingTable = {}
        for segment, replicas in external_view.items():
            candidates = [s for s, st in replicas.items() if st in ONLINE_STATES]
            if not candidates:
                continue  # segment currently unserved -> partial results
            server = rng.choice(candidates)
            table.setdefault(server, []).append(segment)
        out.append(table)
    return out


class RoutingTableProvider:
    """Per-table routing state, rebuilt on external-view updates (the
    broker's ExternalView listener analog)."""

    def __init__(self, num_tables: int = 10) -> None:
        self._routing: Dict[str, List[RoutingTable]] = {}
        self._lock = threading.Lock()
        self._num_tables = num_tables
        self._rng = random.Random(7)

    def update(self, table_name: str, external_view: ExternalView) -> None:
        tables = balanced_random_routing_tables(
            external_view, self._num_tables, seed=self._rng.randrange(1 << 30)
        )
        with self._lock:
            self._routing[table_name] = tables

    def remove(self, table_name: str) -> None:
        with self._lock:
            self._routing.pop(table_name, None)

    def find_servers(self, table_name: str) -> Optional[RoutingTable]:
        with self._lock:
            tables = self._routing.get(table_name)
            if not tables:
                return None
            return self._rng.choice(tables)

    def tables(self) -> List[str]:
        with self._lock:
            return list(self._routing.keys())
