"""Broker routing: external view -> precomputed routing tables.

The reference listens to Helix ExternalView changes and precomputes N
routing tables per table — each a full ``{server -> segment set}``
cover with one random ONLINE replica chosen per segment — then picks a
random table per query (``HelixExternalViewBasedRouting.java:65``,
``BalancedRandomRoutingTableBuilder.java``).  Same design here, fed by
the controller's external view (``pinot_tpu.controller``) or a static
map.

Resilience extensions: the provider keeps the raw external view, so it
can (a) consult a ``ServerHealthTracker`` in ``find_servers`` and
re-cover segments whose chosen replica sits in the penalty box, and
(b) answer ``alternates`` — "who else serves these segments?" — which
is what the broker's retry-with-failover and hedging paths use to
re-issue a straggler's segment set to a different replica.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Set, Tuple

# external view shape: {segment_name: {server_name: state}}; state in
# ONLINE | CONSUMING | OFFLINE | ERROR
ExternalView = Dict[str, Dict[str, str]]
RoutingTable = Dict[str, List[str]]  # server -> segments

ONLINE_STATES = ("ONLINE", "CONSUMING")


def balanced_random_routing_tables(
    external_view: ExternalView, num_tables: int = 10, seed: int = 0
) -> List[RoutingTable]:
    """Precompute N random replica-balanced covers of all segments."""
    rng = random.Random(seed)
    out: List[RoutingTable] = []
    for _ in range(max(1, num_tables)):
        table: RoutingTable = {}
        for segment, replicas in external_view.items():
            candidates = [s for s, st in replicas.items() if st in ONLINE_STATES]
            if not candidates:
                continue  # segment currently unserved -> partial results
            server = rng.choice(candidates)
            table.setdefault(server, []).append(segment)
        out.append(table)
    return out


class RoutingTableProvider:
    """Per-table routing state, rebuilt on external-view updates (the
    broker's ExternalView listener analog)."""

    def __init__(self, num_tables: int = 10) -> None:
        self._routing: Dict[str, List[RoutingTable]] = {}
        self._views: Dict[str, ExternalView] = {}
        self._lock = threading.Lock()
        self._num_tables = num_tables
        self._rng = random.Random(7)

    def update(self, table_name: str, external_view: ExternalView) -> None:
        tables = balanced_random_routing_tables(
            external_view, self._num_tables, seed=self._rng.randrange(1 << 30)
        )
        view_copy = {seg: dict(replicas) for seg, replicas in external_view.items()}
        with self._lock:
            self._routing[table_name] = tables
            self._views[table_name] = view_copy

    def remove(self, table_name: str) -> None:
        with self._lock:
            self._routing.pop(table_name, None)
            self._views.pop(table_name, None)

    def find_servers(self, table_name: str, health=None) -> Optional[RoutingTable]:
        """Pick a precomputed cover; with a health tracker, re-route any
        segment whose chosen replica is unhealthy onto a healthy replica
        (falling back to the original pick when no replica is healthy —
        sending to a penalty-boxed server beats not sending at all).
        A still-warming replica (restart in prewarm) is deprioritized
        the same way but never excluded: healthy-and-ready replicas win,
        a warming replica still serves when it is all that is left."""
        with self._lock:
            tables = self._routing.get(table_name)
            if not tables:
                return None
            choice = self._rng.choice(tables)
            if health is None:
                return choice
            is_warming = getattr(health, "is_warming", None) or (lambda s: False)
            if all(health.is_healthy(s) and not is_warming(s) for s in choice):
                return choice
            view = self._views.get(table_name, {})
            rerouted: RoutingTable = {}
            for server, segments in choice.items():
                if health.is_healthy(server) and not is_warming(server):
                    rerouted.setdefault(server, []).extend(segments)
                    continue
                for segment in segments:
                    online = [
                        s
                        for s, st in view.get(segment, {}).items()
                        if st in ONLINE_STATES
                    ]
                    healthy = [s for s in online if health.is_healthy(s)]
                    ready = [s for s in healthy if not is_warming(s)]
                    candidates = ready or (
                        [server] if health.is_healthy(server) else healthy
                    )
                    picked = self._rng.choice(candidates) if candidates else server
                    rerouted.setdefault(picked, []).append(segment)
            return rerouted

    def has_alternate(
        self, table_name: str, segments: List[str], exclude: Set[str]
    ) -> bool:
        """Cheap existence check: could ANY of these segments be
        re-issued to a replica outside ``exclude``?  (Hot path — called
        per attempt to size the attempt timeout; avoids building the
        full re-cover that ``alternates`` returns.)"""
        with self._lock:
            view = self._views.get(table_name)
            if view is None:
                return False
            for segment in segments:
                for s, st in view.get(segment, {}).items():
                    if st in ONLINE_STATES and s not in exclude:
                        return True
            return False

    def alternates(
        self,
        table_name: str,
        segments: List[str],
        exclude: Set[str],
        health=None,
    ) -> Tuple[RoutingTable, List[str]]:
        """Re-cover ``segments`` with replicas outside ``exclude``.

        Returns ``(assignment, unserved)``: the failover routing table
        plus any segments with no remaining replica.  Healthy replicas
        are preferred; a penalty-boxed replica is still used when it is
        the only one left (last-resort attempt beats giving up).
        """
        with self._lock:
            view = self._views.get(table_name)
            if view is None:
                return {}, list(segments)
            assignment: RoutingTable = {}
            unserved: List[str] = []
            for segment in segments:
                candidates = [
                    s
                    for s, st in view.get(segment, {}).items()
                    if st in ONLINE_STATES and s not in exclude
                ]
                if not candidates:
                    unserved.append(segment)
                    continue
                if health is not None:
                    healthy = [s for s in candidates if health.is_healthy(s)]
                    if healthy:
                        candidates = healthy
                    is_warming = getattr(health, "is_warming", None)
                    if is_warming is not None:
                        ready = [s for s in candidates if not is_warming(s)]
                        if ready:
                            candidates = ready
                assignment.setdefault(self._rng.choice(candidates), []).append(segment)
            return assignment, unserved

    def tables(self) -> List[str]:
        with self._lock:
            return list(self._routing.keys())

    def view_of(self, table_name: str) -> Optional[ExternalView]:
        """Copy of the raw external view for a table (the join planner
        reads it to place colocated build sides and to find shuffle
        owners' alternates)."""
        with self._lock:
            view = self._views.get(table_name)
            if view is None:
                return None
            return {seg: dict(replicas) for seg, replicas in view.items()}
