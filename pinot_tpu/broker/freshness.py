"""Event-time freshness plane (ISSUE 19).

``ingest.lag.*`` (PR 15) measures how far a consumer trails its stream
in *offsets* — a queue-depth signal that says nothing about how stale
the data actually is.  This module tracks **event time**: realtime
consumers advance a per-(table, partition) watermark to the maximum
value of the schema time column they have indexed (converted to epoch
milliseconds via the time field's declared unit), and the serving path
derives every freshness surface from those watermarks:

- servers stamp ``IntermediateResult.freshness = {"minEventMs": ...}``
  (min over the served table's partitions) — a trailing optional
  DataTable field, mixed-version safe like cost/plan_info;
- the broker merges the per-server stamps with MIN semantics and
  surfaces ``freshnessMs = now − minEventMs`` on the BrokerResponse,
  in the slow-query log, in EXPLAIN, and as ``freshness.*`` series;
- ``freshnessTargetMs`` rides the PR 11 SLO burn-rate machinery as a
  third objective (utils/slo.py).

The registry is **process-global** (like ``engine.device.LEDGER`` and
``engine.residency.RESIDENCY``): one consumer per (table, partition)
exists per process in production, and in-process multi-server harnesses
share the stream anyway, so replicas advancing the same key converge on
the same value.  Watermarks are keyed on (table, partition), NOT on
segment — so they survive segment rollover (the successor consuming
segment keeps advancing the same key) and consumer pool resizes.

Deliberately stdlib-only: servers and realtime consumers import this
module, so it must not pull broker machinery in.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


def now_ms() -> float:
    return time.time() * 1000.0


class EventTimeWatermarks:
    """Max ingested event-time (epoch ms) per (table, partition).

    ``advance`` is monotone: late/duplicate batches (commit-retry
    replays, out-of-order event time inside the stream) can never move
    a watermark backwards — ``freshnessMs`` derived from it is then
    monotone-consistent with what was actually consumed.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (table, partition) -> max event-time ms
        self._marks: Dict[tuple, float] = {}

    def advance(self, table: str, partition: int, event_ms: float) -> None:
        if event_ms is None:
            return
        key = (str(table), int(partition))
        with self._lock:
            cur = self._marks.get(key)
            if cur is None or event_ms > cur:
                self._marks[key] = float(event_ms)

    def get(self, table: str, partition: int) -> Optional[float]:
        return self._marks.get((str(table), int(partition)))

    def table_min_ms(self, table: str) -> Optional[float]:
        """The serving stamp: min over the table's partition watermarks
        (an answer is only as fresh as its stalest partition), or None
        when no partition of ``table`` has consumed anything yet."""
        table = str(table)
        with self._lock:
            vals = [v for (t, _p), v in self._marks.items() if t == table]
        return min(vals) if vals else None

    def tables(self) -> List[str]:
        with self._lock:
            return sorted({t for t, _p in self._marks})

    def drop_table(self, table: str) -> None:
        """Table deletion hook (tests / controller cleanup)."""
        table = str(table)
        with self._lock:
            for key in [k for k in self._marks if k[0] == table]:
                self._marks.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._marks.clear()

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/audit`` freshness section: per-table min/max
        watermarks and the implied lag right now."""
        now = now_ms()
        with self._lock:
            marks = dict(self._marks)
        per_table: Dict[str, Dict[str, Any]] = {}
        for (table, partition), v in sorted(marks.items()):
            t = per_table.setdefault(
                table, {"partitions": {}, "minEventMs": v, "maxEventMs": v}
            )
            t["partitions"][str(partition)] = v
            t["minEventMs"] = min(t["minEventMs"], v)
            t["maxEventMs"] = max(t["maxEventMs"], v)
        for t in per_table.values():
            t["lagMs"] = round(max(0.0, now - t["minEventMs"]), 3)
        return {"tables": per_table}


# THE process-wide registry (see module docstring for why global).
WATERMARKS = EventTimeWatermarks()


def batch_max_event_ms(values, unit_ms: float) -> Optional[float]:
    """Max event time of one indexed batch, in epoch ms.

    ``values`` is whatever the consumer has for the time column — a
    numpy array (columnar path) or an iterable of row values.  Strings
    and empty batches yield None (no watermark movement: an unparseable
    time column must not fabricate freshness).
    """
    if values is None:
        return None
    try:
        import numpy as np

        arr = np.asarray(values)
        if arr.size == 0 or arr.dtype.kind not in "iuf":
            return None
        return float(arr.max()) * float(unit_ms)
    except (TypeError, ValueError):
        return None


def worst_freshness_tables(
    snapshot: Dict[str, Any], top: int = 5
) -> List[Dict[str, Any]]:
    """Doctor/postmortem helper: the ``top`` stalest tables out of an
    ``EventTimeWatermarks.snapshot()`` payload, worst first."""
    tables = (snapshot or {}).get("tables") or {}
    ranked = sorted(
        (
            {"table": name, "lagMs": info.get("lagMs", 0.0)}
            for name, info in tables.items()
        ),
        key=lambda e: -e["lagMs"],
    )
    return ranked[: max(0, top)]
