"""Per-table query rate limiting (QuotaConfig.maxQueriesPerSecond
enforcement — the reference stores the quota in table config
(``common/config/QuotaConfig``) and brokers enforce it)."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _TokenBucket:
    def __init__(self, qps: float) -> None:
        self.qps = qps
        self.capacity = max(qps, 1.0)
        self.tokens = self.capacity
        self.last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        # caller holds self._lock
        now = time.monotonic()
        self.tokens = min(self.capacity, self.tokens + (now - self.last) * self.qps)
        self.last = now

    def try_acquire(self) -> bool:
        with self._lock:
            self._refill()
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False

    def headroom(self) -> float:
        """Fraction of the bucket currently unspent (peek, no acquire)."""
        with self._lock:
            self._refill()
            return self.tokens / self.capacity


class QueryQuotaManager:
    def __init__(self) -> None:
        self._buckets: Dict[str, _TokenBucket] = {}
        self._lock = threading.Lock()

    def set_quota(self, table: str, qps: Optional[float]) -> None:
        with self._lock:
            if qps and qps > 0:
                self._buckets[table] = _TokenBucket(qps)
            else:
                self._buckets.pop(table, None)

    def allow(self, table: str) -> bool:
        with self._lock:
            bucket = self._buckets.get(table)
        return bucket.try_acquire() if bucket is not None else True

    def headroom(self, table: str) -> float:
        """Fraction of the table's rate budget currently unused (1.0 when
        unlimited).  Hedged requests amplify server load, so the broker
        only hedges while the table has quota headroom — a table already
        brushing its QPS cap must not double its own traffic."""
        with self._lock:
            bucket = self._buckets.get(table)
        return bucket.headroom() if bucket is not None else 1.0
