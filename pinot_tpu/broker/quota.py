"""Per-table query rate limiting (QuotaConfig.maxQueriesPerSecond
enforcement — the reference stores the quota in table config
(``common/config/QuotaConfig``) and brokers enforce it)."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _TokenBucket:
    """Token bucket with FRACTIONAL refill and configurable burst.

    ``qps`` may be < 1.0 (e.g. 0.5 = one query per two seconds): the
    r6 version rounded capacity up to 1.0 AND only admitted on a full
    token, which is correct — but it also seeded a fresh bucket at full
    capacity on every quota re-notify, and capacity==qps for qps >= 1
    left no burst allowance at all.  Now:

    - capacity = ``burst`` if given, else max(qps, 1.0) — a steady
      sub-1-QPS client is admitted every 1/qps seconds, and an explicit
      burst lets a bursty client spend saved-up headroom;
    - ``reconfigure`` updates qps/burst IN PLACE, preserving spent
      tokens (clamped to the new capacity) — a cluster-state re-notify
      must not refill a flooding table's bucket.
    """

    @staticmethod
    def _capacity(qps: float, burst: Optional[float]) -> float:
        # capacity floor of 1.0: acquiring costs a whole token, so a
        # sub-1 burst (misconfigured) would otherwise block EVERY query
        if burst and burst > 0:
            return max(float(burst), 1.0)
        return max(qps, 1.0)

    def __init__(self, qps: float, burst: Optional[float] = None) -> None:
        self.qps = float(qps)
        self.burst = burst
        self.capacity = self._capacity(qps, burst)
        self.tokens = self.capacity
        self.last = time.monotonic()
        self._lock = threading.Lock()

    def reconfigure(self, qps: float, burst: Optional[float] = None) -> None:
        """Apply a quota UPDATE without resetting spent tokens."""
        with self._lock:
            self._refill()
            self.qps = float(qps)
            self.burst = burst
            self.capacity = self._capacity(qps, burst)
            self.tokens = min(self.tokens, self.capacity)

    def _refill(self) -> None:
        # caller holds self._lock
        now = time.monotonic()
        self.tokens = min(self.capacity, self.tokens + (now - self.last) * self.qps)
        self.last = now

    def try_acquire(self) -> bool:
        with self._lock:
            self._refill()
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False

    def headroom(self) -> float:
        """Fraction of the bucket currently unspent (peek, no acquire)."""
        with self._lock:
            self._refill()
            return self.tokens / self.capacity


class QueryQuotaManager:
    def __init__(self) -> None:
        self._buckets: Dict[str, _TokenBucket] = {}
        self._lock = threading.Lock()

    def set_quota(
        self, table: str, qps: Optional[float], burst: Optional[float] = None
    ) -> None:
        """Install/update/remove a table's QPS quota.  An UPDATE of an
        existing bucket reconfigures it in place (tokens preserved) so
        the periodic cluster-state re-notify cannot act as a refill;
        ``qps`` None/<=0 removes the bucket entirely."""
        with self._lock:
            if qps and qps > 0:
                bucket = self._buckets.get(table)
                if bucket is None:
                    self._buckets[table] = _TokenBucket(qps, burst)
                elif bucket.qps != qps or bucket.burst != burst:
                    bucket.reconfigure(qps, burst)
            else:
                self._buckets.pop(table, None)

    def tables(self) -> list:
        """Tables that currently carry a quota (propagation bookkeeping:
        the networked broker clears buckets for tables whose quota left
        the cluster-state snapshot)."""
        with self._lock:
            return list(self._buckets)

    def allow(self, table: str) -> bool:
        with self._lock:
            bucket = self._buckets.get(table)
        return bucket.try_acquire() if bucket is not None else True

    def headroom(self, table: str) -> float:
        """Fraction of the table's rate budget currently unused (1.0 when
        unlimited).  Hedged requests amplify server load, so the broker
        only hedges while the table has quota headroom — a table already
        brushing its QPS cap must not double its own traffic."""
        with self._lock:
            bucket = self._buckets.get(table)
        return bucket.headroom() if bucket is not None else 1.0
