"""Broker join planner + exchange coordinator.

The broker turns a parsed two-table equi-join into one of three
physical strategies (decision order; ``joinStrategy`` debug option /
``PINOT_TPU_JOIN_STRATEGY`` forces one):

1. **colocated** — both tables declare partitioning on their join key
   (``TableConfig.partitioning``), segment names carry their partition
   (``..._pN`` / ``...__pN``), and every server in the probe cover
   locally holds build segments for every partition its probe segments
   span.  One scatter round: each probe server builds from its OWN
   build segments and probes its local probe segments — zero exchange
   bytes.

2. **broadcast** — the build side (right table, filters pushed down)
   fits the budget (``PINOT_TPU_JOIN_BROADCAST_ROWS`` /
   ``_BYTES``): the broker extracts it once from the build cover, then
   ships the SAME dict-encoded payload inside every probe server's
   scatter request.

3. **shuffle** — everything else: both sides extract, and the broker
   (the exchange fabric of this scatter-gather architecture) routes
   key-hash partitions of both sides to owner servers drawn from the
   probe cover.  Heavy-hitter keys — detected from the extracted
   per-key counts (``engine/join.py plan_shuffle_partitions``) — get
   split-and-replicated instead of hot-spotting one owner, so no
   server receives >2x the mean exchange bytes even under zipf keys.

Every phase rides the broker's resilient ``_scatter_gather`` (failover
to replicas, circuit breaker, AIMD windows, deadline propagation), and
every per-server reply's cost vector merges into the final response —
``broker cost == Σ server costs`` holds for joins exactly as for scans
(buildRows / probeRows / shuffleBytes / broadcastBytes are additive
COST_KEYS).  Server-side, every phase request queues through the
fair-share scheduler under its own table, so one tenant's join flood
cannot starve another tenant's scans (tier-1 chaos:
``cluster_harness --scenario join-under-flood``).

The strategy size estimator learns table totals from every merged
response (``TableStatsRegistry``), so EXPLAIN names the strategy real
execution will choose once the tables have been seen; measured build
sizes recorded after each join keep it honest.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from pinot_tpu.common.response import BrokerResponse, ErrorCode, QueryException
from pinot_tpu.engine.join import (
    JoinValidationError,
    SideRows,
    decode_side,
    encode_side,
    merge_sides,
    partition_of_segment,
    plan_shuffle_partitions,
    side_take,
    split_join_filter,
)
from pinot_tpu.engine.plandigest import _raw_table as _raw
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.engine.results import IntermediateResult

OFFLINE_SUFFIX = "_OFFLINE"
REALTIME_SUFFIX = "_REALTIME"


class TableStatsRegistry:
    """Learned per-raw-table size statistics feeding the strategy
    estimator: total docs from every merged scan reply, measured build
    extract rows/bytes after every join."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._docs: Dict[str, int] = {}
        self._build: Dict[str, Tuple[int, int]] = {}  # raw -> (rows, bytes)

    def observe(self, table: str, total_docs: int) -> None:
        with self._lock:
            self._docs[_raw(table)] = int(total_docs)

    def observe_build(self, table: str, rows: int, nbytes: int) -> None:
        with self._lock:
            self._build[_raw(table)] = (int(rows), int(nbytes))

    def estimate(self, table: str) -> Optional[Dict[str, Any]]:
        """Best build-size estimate: a measured extract wins over a
        docs-count guess (8 bytes/row placeholder width)."""
        raw = _raw(table)
        with self._lock:
            b = self._build.get(raw)
            d = self._docs.get(raw)
        if b is not None:
            return {"rows": b[0], "bytes": b[1], "source": "measured"}
        if d is not None:
            return {"rows": d, "bytes": d * 8, "source": "totalDocs"}
        return None


class PartitionRegistry:
    """Declared table partitioning (TableConfig.partitioning), fed by
    the broker starters over the same propagation paths as quotas —
    in-process config apply and the networked clusterstate poll."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_raw: Dict[str, Tuple[str, int]] = {}

    def set_partitioning(
        self, table: str, column: Optional[str], num_partitions: Optional[int]
    ) -> None:
        raw = _raw(table)
        with self._lock:
            if column and num_partitions:
                self._by_raw[raw] = (column, int(num_partitions))
            else:
                self._by_raw.pop(raw, None)

    def get(self, table: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._by_raw.get(_raw(table))


class JoinCoordinator:
    def __init__(self, broker) -> None:
        self.broker = broker
        self.stats = TableStatsRegistry()
        self.partitions = PartitionRegistry()
        for m in (
            "join.queries",
            "join.failed",
            "join.strategy.colocated",
            "join.strategy.broadcast",
            "join.strategy.shuffle",
            "join.heavyHitterSplits",
            "join.shuffleBytes",
            "join.broadcastBytes",
        ):
            broker.metrics.meter(m)
        broker.metrics.timer("join.planMs")

    # -- knobs (read per query: tests flip envs) ----------------------
    @staticmethod
    def _budget_rows() -> int:
        try:
            return int(os.environ.get("PINOT_TPU_JOIN_BROADCAST_ROWS", "100000"))
        except ValueError:
            return 100_000

    @staticmethod
    def _budget_bytes() -> int:
        try:
            return int(os.environ.get("PINOT_TPU_JOIN_BROADCAST_BYTES", str(4 << 20)))
        except ValueError:
            return 4 << 20

    @staticmethod
    def _split_enabled() -> bool:
        return os.environ.get("PINOT_TPU_JOIN_SPLIT", "1") not in ("0", "false")

    @staticmethod
    def _heavy_factor() -> float:
        try:
            return float(os.environ.get("PINOT_TPU_JOIN_HEAVY_FACTOR", "0.5"))
        except ValueError:
            return 0.5

    # ------------------------------------------------------------------
    def handle(
        self, request, pql: str, timeout_ms: float, request_id: str, ctx, table: str
    ) -> BrokerResponse:
        t0 = time.perf_counter()
        deadline = time.monotonic() + timeout_ms / 1000.0
        spec = request.join
        try:
            split_join_filter(request)  # mixed-side predicates -> typed 4xx
            left_phys = self._resolve_physical(table)
            right_phys = self._resolve_physical(spec.right_table)
            # inside the try: a bogus client-supplied joinStrategy is a
            # typed 4xx too, never an unhandled broker exception
            forced = self._forced_strategy(request)
        except JoinValidationError as e:
            return BrokerResponse(
                exceptions=[QueryException(ErrorCode.QUERY_VALIDATION, str(e))]
            )
        m = self.broker.metrics
        m.meter("join.queries").mark()
        colo = self._colocated_plan(left_phys, right_phys, spec)
        est = self.stats.estimate(spec.right_table)

        if request.explain == "plan":
            node = self._plan_node(spec, colo, est, forced, executed=None)
            resp = BrokerResponse()
            resp.explain = self._explain_shell(request, "plan", node)
            m.timer("join.planMs").update((time.perf_counter() - t0) * 1000)
            return resp

        if forced == "colocated" and not colo["eligible"]:
            return BrokerResponse(
                exceptions=[
                    QueryException(
                        ErrorCode.QUERY_VALIDATION,
                        "joinStrategy=colocated forced but the tables are not "
                        f"colocated: {colo['reason']}",
                    )
                ]
            )

        try:
            resp, executed = self._execute(
                request, pql, spec, left_phys, right_phys, colo, est, forced,
                deadline, request_id, ctx, table,
            )
        except JoinValidationError as e:
            return BrokerResponse(
                exceptions=[QueryException(ErrorCode.QUERY_VALIDATION, str(e))]
            )
        m.meter(f"join.strategy.{executed['strategy']}").mark()
        if executed.get("shuffleBytes"):
            m.meter("join.shuffleBytes").mark(int(executed["shuffleBytes"]))
        if executed.get("broadcastBytes"):
            m.meter("join.broadcastBytes").mark(int(executed["broadcastBytes"]))
        if executed.get("heavyHitterSplits"):
            m.meter("join.heavyHitterSplits").mark(int(executed["heavyHitterSplits"]))
        if resp.exceptions:
            m.meter("join.failed").mark()
        if request.explain == "analyze":
            node = self._plan_node(spec, colo, est, forced, executed=executed)
            resp.explain = self._explain_shell(request, "analyze", node)
            resp.explain["actualCost"] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in sorted(resp.cost.items())
            }
            resp.explain["actualDocsScanned"] = resp.num_docs_scanned
        m.timer("join.planMs").update((time.perf_counter() - t0) * 1000)
        return resp

    # -- planning pieces ----------------------------------------------
    @staticmethod
    def _forced_strategy(request) -> Optional[str]:
        forced = (request.debug_options or {}).get("joinStrategy") or os.environ.get(
            "PINOT_TPU_JOIN_STRATEGY"
        )
        if not forced:
            return None
        forced = str(forced).lower()
        if forced not in ("colocated", "broadcast", "shuffle"):
            raise JoinValidationError(
                f"unknown joinStrategy {forced!r} (colocated|broadcast|shuffle)"
            )
        return forced

    def _resolve_physical(self, table: str) -> str:
        known = set(self.broker.routing.tables())
        if table in known:
            return table
        offline, realtime = table + OFFLINE_SUFFIX, table + REALTIME_SUFFIX
        if offline in known and realtime in known:
            raise JoinValidationError(
                f"table {table} is hybrid (OFFLINE + REALTIME): hybrid join "
                "sides are not supported yet"
            )
        if offline in known:
            return offline
        if realtime in known:
            return realtime
        raise JoinValidationError(f"no routing for join table {table}")

    def _colocated_plan(self, left_phys: str, right_phys: str, spec) -> Dict[str, Any]:
        """Colocation verdict + (when eligible) the probe cover and the
        per-server build segment lists."""
        lp = self.partitions.get(left_phys)
        rp = self.partitions.get(right_phys)
        if lp is None or rp is None:
            return {"eligible": False, "reason": "partitioning not declared on both tables"}
        if lp[0] != spec.left_key or rp[0] != spec.right_key:
            return {
                "eligible": False,
                "reason": "partition columns do not match the join keys "
                f"({lp[0]}/{rp[0]} vs {spec.left_key}/{spec.right_key})",
            }
        if lp[1] != rp[1]:
            return {
                "eligible": False,
                "reason": f"partition counts differ ({lp[1]} vs {rp[1]})",
            }
        cover = self.broker.routing.find_servers(left_phys, health=self.broker.health)
        right_view = self.broker.routing.view_of(right_phys)
        if not cover or not right_view:
            return {"eligible": False, "reason": "no live cover for one side"}
        server_build: Dict[str, List[str]] = {}
        for seg, replicas in right_view.items():
            for srv, st in replicas.items():
                if st in ("ONLINE", "CONSUMING"):
                    server_build.setdefault(srv, []).append(seg)
        build_segments: Dict[str, List[str]] = {}
        for server, probe_segs in cover.items():
            probe_parts = {partition_of_segment(s) for s in probe_segs}
            if None in probe_parts:
                return {
                    "eligible": False,
                    "reason": "probe segments without partition ids",
                }
            local = server_build.get(server, [])
            local_parts = {partition_of_segment(s) for s in local}
            if not probe_parts <= local_parts:
                return {
                    "eligible": False,
                    "reason": f"server {server} lacks local build partitions "
                    f"{sorted(probe_parts - local_parts)}",
                }
            build_segments[server] = sorted(
                s for s in local if partition_of_segment(s) in probe_parts
            )
        return {
            "eligible": True,
            "reason": "partition-aligned covers",
            "cover": cover,
            "build_segments": build_segments,
            "server_build": server_build,
        }

    def _size_strategy(self, est: Optional[Dict[str, Any]]) -> Optional[str]:
        if est is None:
            return None
        within = (
            est["rows"] <= self._budget_rows() and est["bytes"] <= self._budget_bytes()
        )
        return "broadcast" if within else "shuffle"

    def _plan_node(
        self, spec, colo, est, forced, executed: Optional[Dict[str, Any]]
    ) -> Dict[str, Any]:
        if executed is not None:
            strategy = executed["strategy"]
        elif forced:
            strategy = forced
        elif colo["eligible"]:
            strategy = "colocated"
        else:
            strategy = self._size_strategy(est) or "broadcast|shuffle (size probe at execution)"
        node: Dict[str, Any] = {
            "strategy": strategy,
            "forced": bool(forced),
            "on": f"{spec.left_key} = {spec.right_table}.{spec.right_key}",
            "colocated": {"eligible": colo["eligible"], "reason": colo["reason"]},
            "build": {
                "table": spec.right_table,
                "estRows": est["rows"] if est else None,
                "estBytes": est["bytes"] if est else None,
                "estSource": est["source"] if est else None,
            },
            "budget": {
                "broadcastRows": self._budget_rows(),
                "broadcastBytes": self._budget_bytes(),
            },
            "skew": {
                "splitEnabled": self._split_enabled(),
                "heavyFactor": self._heavy_factor(),
            },
        }
        if executed is not None:
            node["actual"] = {
                k: executed[k]
                for k in (
                    "strategy",
                    "buildRows",
                    "probeRows",
                    "broadcastBytes",
                    "shuffleBytes",
                    "heavyHitterSplits",
                    "shuffleBytesPerServer",
                    "owners",
                )
                if k in executed
            }
        return node

    def _explain_shell(self, request, mode: str, node: Dict[str, Any]) -> Dict[str, Any]:
        from pinot_tpu.engine.plandigest import plan_shape_digest, plan_shape_summary

        return {
            "mode": mode,
            "planDigest": plan_shape_digest(request),
            "summary": plan_shape_summary(request),
            "numServers": 0,
            "tierCounts": {},
            "estimatedCost": {"bytesScanned": int(node["build"].get("estBytes") or 0)},
            "join": node,
            "servers": [],
        }

    # -- execution -----------------------------------------------------
    def _remaining_ms(self, deadline: float) -> float:
        return max(1.0, (deadline - time.monotonic()) * 1000.0)

    def _cover_batches(self, phys: str, pql: str):
        from pinot_tpu.broker.broker import _Batch

        cover = self.broker.routing.find_servers(phys, health=self.broker.health)
        if not cover:
            return None, None
        batches = [
            _Batch(phys, pql, segments, server, order=i)
            for i, (server, segments) in enumerate(sorted(cover.items()))
        ]
        return cover, batches

    def _execute(
        self, request, pql, spec, left_phys, right_phys, colo, est, forced,
        deadline, request_id, ctx, table,
    ) -> Tuple[BrokerResponse, Dict[str, Any]]:
        sg_union = {
            "servers_queried": set(),
            "servers_responded": set(),
            "retries": 0,
            "hedges": 0,
            "unserved": [],
            "server_traces": [],
        }
        exceptions: List[QueryException] = []
        all_parts: List[IntermediateResult] = []
        executed: Dict[str, Any] = {}

        def run_phase(phys: str, batches, extra_fn, span: str):
            with ctx.span(span, servers=len(batches)):
                parts, sg = self.broker._scatter_gather(
                    request,
                    batches,
                    self._remaining_ms(deadline),
                    table,
                    request_id,
                    ctx,
                    extra_fn=extra_fn,
                )
            exceptions.extend(sg["exceptions"])
            sg_union["servers_queried"] |= sg["servers_queried"]
            sg_union["servers_responded"] |= sg["servers_responded"]
            sg_union["retries"] += sg["retries"]
            sg_union["hedges"] += sg["hedges"]
            sg_union["unserved"].extend(sg["unserved"])
            sg_union["server_traces"].extend(sg["server_traces"])
            return parts

        strategy = forced if forced else ("colocated" if colo["eligible"] else None)

        if strategy == "colocated":
            build_map = colo["build_segments"]
            server_build = colo.get("server_build", {})
            from pinot_tpu.broker.broker import _Batch

            batches = [
                _Batch(left_phys, pql, segments, server, order=i)
                for i, (server, segments) in enumerate(sorted(colo["cover"].items()))
            ]

            def extra_fn(server: str) -> Dict[str, Any]:
                # failover children recompute for THEIR server: any
                # local build segments it holds (the server re-checks
                # partition coverage against the probe segments it
                # actually serves and 230s when it cannot)
                segs = build_map.get(server)
                if segs is None:
                    segs = sorted(server_build.get(server, []))
                return {
                    "phase": "exec",
                    "strategy": "colocated",
                    "buildTable": right_phys,
                    "buildSegments": segs,
                }

            all_parts.extend(run_phase(left_phys, batches, extra_fn, "joinColocated"))
            executed.update({"strategy": "colocated"})
        else:
            # -- phase 1a: build-side extraction --------------------------
            cover, batches = self._cover_batches(right_phys, pql)
            if batches is None:
                raise JoinValidationError(
                    f"no servers currently serving join table {right_phys}"
                )
            extract_extra = {"phase": "extract", "side": "build"}
            bparts = run_phase(
                right_phys, batches, lambda s: dict(extract_extra), "joinBuildExtract"
            )
            build = merge_sides(
                [decode_side(p.join_payload) for p in bparts if p.join_payload]
            )
            for p in bparts:
                p.join_payload = None
            all_parts.extend(bparts)
            self.stats.observe_build(spec.right_table, build.n, build.nbytes())
            executed["buildRows"] = build.n
            if strategy is None:
                # the JUST-measured extract is exact and in hand: it
                # always wins over a learned estimate (a stale small
                # estimate must not broadcast an over-budget build side)
                strategy = self._size_strategy(
                    {"rows": build.n, "bytes": build.nbytes(), "source": "measured"}
                )
            executed["strategy"] = strategy

            if strategy == "broadcast":
                payload = encode_side(build)
                _cov, pbatches = self._cover_batches(left_phys, pql)
                if pbatches is None:
                    raise JoinValidationError(
                        f"no servers currently serving join table {left_phys}"
                    )
                exec_extra = {
                    "phase": "exec",
                    "strategy": "broadcast",
                    "build": payload,
                }
                eparts = run_phase(
                    left_phys, pbatches, lambda s: exec_extra, "joinBroadcast"
                )
                all_parts.extend(eparts)
                executed["broadcastBytes"] = build.nbytes() * max(1, len(pbatches))
            else:
                # -- phase 1b: probe-side extraction ----------------------
                _cov, pbatches = self._cover_batches(left_phys, pql)
                if pbatches is None:
                    raise JoinValidationError(
                        f"no servers currently serving join table {left_phys}"
                    )
                # owners: EVERY live server holding any probe replica —
                # not just the cover draw — so small tables still
                # spread partitions and an owner death has alternates.
                # Penalty-boxed servers are excluded up front (they
                # remain failover alternates of last resort only).
                view = self.broker.routing.view_of(left_phys) or {}
                candidates = {
                    srv
                    for replicas in view.values()
                    for srv, st in replicas.items()
                    if st in ("ONLINE", "CONSUMING")
                } or {b.server for b in pbatches}
                healthy = {
                    s for s in candidates if self.broker.health.is_healthy(s)
                }
                owners = sorted(healthy or candidates)
                pparts = run_phase(
                    left_phys,
                    pbatches,
                    lambda s: {"phase": "extract", "side": "probe"},
                    "joinProbeExtract",
                )
                probe = merge_sides(
                    [decode_side(p.join_payload) for p in pparts if p.join_payload]
                )
                for p in pparts:
                    p.join_payload = None
                all_parts.extend(pparts)
                executed["probeRows"] = probe.n

                # -- phase 2: skew-aware exchange + owner execution -------
                assignments, n_heavy = plan_shuffle_partitions(
                    build,
                    probe,
                    len(owners),
                    split_heavy=self._split_enabled(),
                    heavy_factor=self._heavy_factor(),
                )
                executed["heavyHitterSplits"] = n_heavy
                executed["owners"] = len(owners)
                eparts, per_server, shuffle_excs = self._dispatch_shuffle(
                    request, pql, left_phys, owners, assignments, build, probe,
                    deadline, request_id, ctx, sg_union,
                )
                exceptions.extend(shuffle_excs)
                all_parts.extend(eparts)
                executed["shuffleBytes"] = sum(per_server.values())
                executed["shuffleBytesPerServer"] = per_server

        for code, msg in [
            (c, m) for p in all_parts for c, m in p.exceptions
        ]:
            exceptions.append(QueryException(code, msg))
        for p in all_parts:
            p.exceptions = []
        with ctx.span("reduce", parts=len(all_parts)):
            resp = reduce_to_response(request, all_parts, exceptions)
        resp.num_servers_queried = len(sg_union["servers_queried"])
        resp.num_servers_responded = len(sg_union["servers_responded"])
        resp.num_segments_unserved = len(sg_union["unserved"])
        # lost shuffle partitions land in "unserved" too (the
        # join-partitions:N marker from _dispatch_shuffle)
        resp.partial_response = bool(sg_union["unserved"])
        resp.num_retries = sg_union["retries"]
        resp.num_hedges = sg_union["hedges"]
        resp._server_traces = sg_union["server_traces"]
        # actuals off the merged cost vector (covers colocated, whose
        # rows are only known server-side)
        executed.setdefault("buildRows", int(resp.cost.get("buildRows", 0)))
        executed.setdefault("probeRows", int(resp.cost.get("probeRows", 0)))
        # per-table cost attribution, as the single-table path does
        self.broker.metrics.meter("cost.docsScanned").mark(int(resp.num_docs_scanned))
        self.broker.metrics.meter("cost.bytesScanned").mark(
            int(resp.cost.get("bytesScanned", 0))
        )
        self.broker.metrics.meter(f"table.{table}.docsScanned").mark(
            int(resp.num_docs_scanned)
        )
        return resp, executed

    def _dispatch_shuffle(
        self, request, pql, left_phys, owners, assignments, build, probe,
        deadline, request_id, ctx, sg_union,
    ):
        """Phase-2 owner dispatch: each owner receives its build/probe
        partitions and executes the hash join; an owner failure retries
        its partition on the remaining owners (the payload is
        broker-held, so ANY server can execute it) before degrading to
        a partial response."""
        import concurrent.futures

        exceptions: List[QueryException] = []
        per_server: Dict[str, int] = {}
        parts: List[IntermediateResult] = []
        payloads: List[Tuple[str, Dict[str, Any], int]] = []
        for owner, (b_idx, p_idx) in zip(owners, assignments):
            b_sub = side_take(build, b_idx)
            p_sub = side_take(probe, p_idx)
            extra = {
                "phase": "exec",
                "strategy": "shuffle",
                "build": encode_side(b_sub),
                "probe": encode_side(p_sub),
            }
            payloads.append((owner, extra, b_sub.nbytes() + p_sub.nbytes()))

        def send(server: str, extra: Dict[str, Any]):
            return self.broker._send_one(
                server,
                left_phys,
                pql,
                [],
                request.enable_trace,
                request.debug_options or None,
                self._remaining_ms(deadline),
                None,
                request_id,
                extra,
            )

        def submit(server: str, extra: Dict[str, Any]):
            # the same per-attempt accounting every _scatter_gather
            # attempt performs: half-open circuit probe claim + AIMD
            # window in/out, so shuffle exec traffic is visible to the
            # congestion controller and the breaker
            self.broker.health.allow_request(server)
            self.broker.admission.on_attempt_start(server)
            fut = self.broker._pool.submit(send, server, extra)
            fut.add_done_callback(
                lambda f, s=server: self.broker._observe_attempt(f, s)
            )
            return fut

        futs = {
            submit(owner, extra): (i, owner, extra, nbytes)
            for i, (owner, extra, nbytes) in enumerate(payloads)
        }
        failed_partitions = 0
        with ctx.span("joinShuffleExec", owners=len(payloads)):
            pending = dict(futs)
            while pending:
                done, _ = concurrent.futures.wait(
                    list(pending.keys()),
                    timeout=max(0.0, deadline - time.monotonic()),
                    return_when=concurrent.futures.FIRST_COMPLETED,
                )
                if not done:
                    for _f, (_i, owner, _e, _n) in pending.items():
                        exceptions.append(
                            QueryException(
                                ErrorCode.BROKER_TIMEOUT,
                                f"join owner {owner}: no reply within deadline",
                            )
                        )
                        failed_partitions += 1
                    break
                for fut in done:
                    i, owner, extra, nbytes = pending.pop(fut)
                    sg_union["servers_queried"].add(owner)
                    try:
                        result = fut.result()
                        retryable = result.exceptions and all(
                            c
                            in (
                                ErrorCode.SERVER_SCHEDULER_DOWN,
                                ErrorCode.SERVER_SHUTTING_DOWN,
                            )
                            for c, _m in result.exceptions
                        )
                        if retryable:
                            raise RuntimeError(result.exceptions[0][1])
                    except Exception as e:
                        self.broker.health.record_failure(owner)
                        tried = extra.setdefault("_tried", [owner])
                        if owner not in tried:
                            tried.append(owner)
                        alternates = [o for o in owners if o not in tried]
                        if alternates and time.monotonic() < deadline:
                            alt = alternates[0]
                            extra["_tried"] = tried + [alt]
                            sg_union["retries"] += 1
                            ctx.event(
                                "joinOwnerFailover", fromServer=owner, toServer=alt
                            )
                            clean = {
                                k: v for k, v in extra.items() if k != "_tried"
                            }
                            nf = submit(alt, clean)
                            pending[nf] = (i, alt, extra, nbytes)
                            continue
                        exceptions.append(
                            QueryException(
                                ErrorCode.BROKER_GATHER,
                                f"join owner {owner}: {type(e).__name__}: {e}",
                            )
                        )
                        failed_partitions += 1
                        continue
                    self.broker.health.record_success(owner)
                    sg_union["servers_responded"].add(owner)
                    per_server[owner] = per_server.get(owner, 0) + nbytes
                    if result.trace:
                        sg_union["server_traces"].append(
                            (None, {k: list(v) for k, v in result.trace.items()})
                        )
                    parts.append(result)
        if failed_partitions:
            # a lost partition means missing joined rows: degrade
            # honestly, exactly like unserved segments
            sg_union["unserved"].append(f"join-partitions:{failed_partitions}")
        return parts, per_server, exceptions
