from pinot_tpu.broker.routing import RoutingTableProvider, balanced_random_routing_tables
from pinot_tpu.broker.broker import BrokerRequestHandler, BrokerHttpServer

__all__ = [
    "RoutingTableProvider",
    "balanced_random_routing_tables",
    "BrokerRequestHandler",
    "BrokerHttpServer",
]
