"""Broker slow-query log: a ring buffer of recent notable queries.

Every query updates the totals; a query is RECORDED into the ring when
it is slow (``timeUsedMs >= threshold``), failed (any exception), or
degraded (``partialResponse``) — the three cases an operator pages
through ``/debug/queries`` to find.  The ring keeps the last N entries
(oldest evicted), each carrying the latency breakdown, the requestId
(correlates with the client's response and any captured trace), the
scatter health counters, and the merged per-query cost vector
(``numDocsScanned`` + ``cost`` — rows/bytes scanned, device vs host
kernel ms, serving-tier segment counts; engine/results.py COST_KEYS) so
"why was this slow" is answerable from the log entry alone.

Env knobs:

- ``PINOT_TPU_SLOW_QUERY_MS``     slow threshold, default 500 ms
- ``PINOT_TPU_SLOW_QUERY_LOG_N``  ring capacity, default 128
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional


class SlowQueryLog:
    def __init__(
        self,
        capacity: Optional[int] = None,
        threshold_ms: Optional[float] = None,
    ) -> None:
        if capacity is None:
            capacity = int(os.environ.get("PINOT_TPU_SLOW_QUERY_LOG_N", "128"))
        if threshold_ms is None:
            threshold_ms = float(os.environ.get("PINOT_TPU_SLOW_QUERY_MS", "500"))
        self.capacity = max(1, capacity)
        self.threshold_ms = threshold_ms
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total_queries = 0
        self.total_recorded = 0

    def observe(self, entry: Dict[str, Any]) -> bool:
        """Count the query; record it into the ring when notable.
        Returns True when the entry was recorded."""
        notable = (
            entry.get("timeUsedMs", 0.0) >= self.threshold_ms
            or bool(entry.get("exceptions"))
            or bool(entry.get("partialResponse"))
        )
        with self._lock:
            self.total_queries += 1
            if notable:
                self.total_recorded += 1
                self._ring.append(dict(entry, ts=round(time.time(), 3)))
        return notable

    def annotate(self, request_id: str, **kv: Any) -> bool:
        """Post-hoc enrichment of a recorded entry (the audit plane's
        ``auditRef`` cross-link lands AFTER the query was logged — the
        audit runs asynchronously).  Returns True when the entry was
        still in the ring."""
        if not request_id:
            return False
        with self._lock:
            for entry in self._ring:
                if entry.get("requestId") == request_id:
                    entry.update(kv)
                    return True
        return False

    def entries(self) -> List[Dict[str, Any]]:
        """Newest first."""
        with self._lock:
            return list(reversed(self._ring))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "thresholdMs": self.threshold_ms,
                "capacity": self.capacity,
                "totalQueries": self.total_queries,
                "totalRecorded": self.total_recorded,
                "entries": list(reversed(self._ring)),
            }
