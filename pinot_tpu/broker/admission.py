"""Broker adaptive admission control: the front door of the
overload-protection plane.

The r6 front door was a single static check — the per-table QPS token
bucket (``broker/quota.py``).  It knows the table's *configured* rate
but nothing about actual cluster saturation: a flooding tenant inside
its QPS quota (or an unquota'd one) would be scattered at saturated
servers until they shed with 210s, burning scatter pool threads,
server queue slots, and retry budget on work that was doomed at
admission time.  The reference's analog is ``QueryQuotaManager`` plus
the scheduler resource limits; production serving stacks put an
adaptive admission layer in front (SRE lore: shed at the cheapest
possible tier).

Three checks, all per-table, all O(1), evaluated in
``BrokerRequestHandler.handle_request``:

1. **QPS token bucket** (``QueryQuotaManager``) — unchanged contract,
   now with fractional QPS + burst (quota.py).
2. **Per-table in-flight cap** — at most ``max_inflight_per_table``
   queries of one table inside the broker at once.  A tenant that
   floods with SLOW queries passes a QPS check for its whole stall
   window; the concurrency cap is what actually bounds its occupancy
   of broker/server resources.
3. **AIMD per-server concurrency windows** — every server gets a
   congestion window (additive increase on a healthy reply,
   multiplicative decrease on a saturated one).  Saturation evidence:
   a 210/SchedulerSaturated reply, a transport failure, or the
   backpressure snapshot servers attach to every reply
   (``IntermediateResult.backpressure``: scheduler pending/maxPending
   and device-lane depth) crossing the high-water fraction.  When a
   query's routing cover has NO server with window headroom left, the
   broker sheds it up front with a typed 429 — before any scatter.

All rejections are ``ErrorCode.TOO_MANY_REQUESTS`` (429) with a
tier-naming message, countable per tier via the ``admission.*`` meters.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.common.conf import env_float as _env_float


class AdmissionDecision:
    """Outcome of ``try_admit``: ``admitted`` plus a shed tier + message
    when refused.  An admitted decision MUST be released (the in-flight
    cap is a counted resource)."""

    __slots__ = ("admitted", "tier", "message")

    def __init__(self, admitted: bool, tier: str = "", message: str = "") -> None:
        self.admitted = admitted
        self.tier = tier
        self.message = message


class _ServerWindow:
    """AIMD congestion window for one server, tracked at the broker.

    ``inflight`` counts this broker's outstanding attempts; ``window``
    moves additively up on success (+increase per reply, capped) and
    multiplicatively down on saturation evidence (x decrease_factor,
    floored at min_window).  The window never blocks an attempt that is
    already routed — it only feeds the pre-scatter admission check and
    observability; a wrong guess degrades to exactly the r6 behavior
    (the server sheds with 210 and the broker fails over)."""

    __slots__ = ("window", "inflight", "saturations")

    def __init__(self, initial: float) -> None:
        self.window = initial
        self.inflight = 0
        self.saturations = 0


class AdmissionController:
    def __init__(
        self,
        quota: Optional[QueryQuotaManager] = None,
        max_inflight_per_table: Optional[int] = None,
        initial_window: Optional[float] = None,
        min_window: float = 1.0,
        max_window: Optional[float] = None,
        increase: float = 0.5,
        decrease_factor: float = 0.5,
        pending_high_water: Optional[float] = None,
        metrics=None,
    ) -> None:
        self.quota = quota or QueryQuotaManager()
        self.max_inflight_per_table = int(
            max_inflight_per_table
            if max_inflight_per_table is not None
            else _env_float("PINOT_TPU_ADMISSION_TABLE_INFLIGHT", 32)
        )
        self.initial_window = float(
            initial_window
            if initial_window is not None
            else _env_float("PINOT_TPU_ADMISSION_WINDOW_INIT", 8)
        )
        self.min_window = min_window
        self.max_window = float(
            max_window
            if max_window is not None
            else _env_float("PINOT_TPU_ADMISSION_WINDOW_MAX", 64)
        )
        self.increase = increase
        self.decrease_factor = decrease_factor
        # fraction of scheduler.maxPending beyond which a reply's
        # backpressure snapshot counts as saturation evidence
        self.pending_high_water = float(
            pending_high_water
            if pending_high_water is not None
            else _env_float("PINOT_TPU_ADMISSION_PENDING_HIGH", 0.8)
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self._table_inflight: Dict[str, int] = {}
        self._windows: Dict[str, _ServerWindow] = {}
        if metrics is not None:
            for m in (
                "admission.shedQuota",
                "admission.shedConcurrency",
                "admission.shedOverload",
                "admission.windowDecreases",
            ):
                metrics.meter(m)
            metrics.gauge("admission.inflight").set_fn(self._total_inflight)

    def _total_inflight(self) -> int:
        with self._lock:
            return sum(self._table_inflight.values())

    # -- front door ----------------------------------------------------
    def try_admit(self, table: str) -> AdmissionDecision:
        """Tier 1+2: QPS bucket, then the per-table in-flight cap.  On
        admit the table's in-flight count is taken and MUST be released
        via ``release``."""
        if not self.quota.allow(table):
            self._mark("admission.shedQuota")
            return AdmissionDecision(
                False,
                "quota",
                f"query rate on table {table} exceeds the configured quota",
            )
        with self._lock:
            n = self._table_inflight.get(table, 0)
            if n >= self.max_inflight_per_table:
                self._mark_locked("admission.shedConcurrency")
                return AdmissionDecision(
                    False,
                    "concurrency",
                    f"table {table} has {n} queries in flight >= "
                    f"per-table cap {self.max_inflight_per_table}",
                )
            self._table_inflight[table] = n + 1
        return AdmissionDecision(True)

    def release(self, table: str) -> None:
        with self._lock:
            n = self._table_inflight.get(table, 0) - 1
            if n > 0:
                self._table_inflight[table] = n
            else:
                self._table_inflight.pop(table, None)

    def table_inflight(self, table: str) -> int:
        with self._lock:
            return self._table_inflight.get(table, 0)

    # -- AIMD windows --------------------------------------------------
    def _window_locked(self, server: str) -> _ServerWindow:
        w = self._windows.get(server)
        if w is None:
            w = self._windows[server] = _ServerWindow(self.initial_window)
        return w

    def check_cover(self, table: str, servers: List[str]) -> AdmissionDecision:
        """Tier 3: pre-scatter overload check.  Admit while ANY server
        in the cover has window headroom; shed with 429 only when every
        one of them is already at (or past) its congestion window —
        scattering then could only end in 210s/timeouts."""
        if not servers:
            return AdmissionDecision(True)
        with self._lock:
            for server in servers:
                w = self._window_locked(server)
                if w.inflight < w.window:
                    return AdmissionDecision(True)
            self._mark_locked("admission.shedOverload")
        return AdmissionDecision(
            False,
            "overload",
            f"all {len(servers)} server(s) covering table {table} are "
            f"saturated (AIMD windows exhausted); shedding at the broker",
        )

    def on_attempt_start(self, server: str) -> None:
        with self._lock:
            self._window_locked(server).inflight += 1

    def on_attempt_cancelled(self, server: str) -> None:
        """A queued attempt was cancelled before it ran (its batch was
        already answered): no health evidence either way — only the
        in-flight count comes back."""
        with self._lock:
            w = self._window_locked(server)
            w.inflight = max(0, w.inflight - 1)

    def on_attempt_done(
        self,
        server: str,
        saturated: bool,
        backpressure: Optional[Dict[str, float]] = None,
    ) -> None:
        """One attempt finished.  ``saturated``: the reply was a 210 /
        transport failure / timeout.  A healthy reply whose backpressure
        snapshot shows the scheduler past the high-water fraction also
        counts as saturation evidence (shed BEFORE the 210s appear)."""
        if not saturated and backpressure:
            try:
                pending = float(backpressure.get("pending", 0))
                cap = float(backpressure.get("maxPending", 0))
                if cap > 0 and pending >= self.pending_high_water * cap:
                    saturated = True
            except (TypeError, ValueError):
                pass
        with self._lock:
            w = self._window_locked(server)
            w.inflight = max(0, w.inflight - 1)
            if saturated:
                w.saturations += 1
                old = w.window
                w.window = max(self.min_window, w.window * self.decrease_factor)
                if w.window < old:
                    self._mark_locked("admission.windowDecreases")
            else:
                w.window = min(self.max_window, w.window + self.increase)

    def window_of(self, server: str) -> float:
        with self._lock:
            return self._window_locked(server).window

    def snapshot(self) -> Dict[str, object]:
        """Ops view (broker /debug/admission)."""
        with self._lock:
            return {
                "maxInflightPerTable": self.max_inflight_per_table,
                "tableInflight": dict(sorted(self._table_inflight.items())),
                "serverWindows": {
                    s: {
                        "window": round(w.window, 2),
                        "inflight": w.inflight,
                        "saturations": w.saturations,
                    }
                    for s, w in sorted(self._windows.items())
                },
            }

    # -- metrics helpers ----------------------------------------------
    def _mark(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.meter(name).mark()

    def _mark_locked(self, name: str) -> None:
        # Meter has its own lock; safe to mark while holding ours
        if self.metrics is not None:
            self.metrics.meter(name).mark()
