"""Broker starter: wires routing + time boundary to external-view updates.

The reference's ``HelixBrokerStarter.java:57`` registers ExternalView
listeners; ``ClusterChangeMediator`` debounces them into routing
rebuilds and time-boundary refreshes.  Here the controller invokes the
listener directly on every view change.
"""
from __future__ import annotations

from typing import Dict

from pinot_tpu.broker.broker import BrokerRequestHandler, OFFLINE_SUFFIX
from pinot_tpu.controller.resource_manager import ClusterResourceManager, InstanceState


class BrokerStarter:
    def __init__(
        self,
        broker: BrokerRequestHandler,
        resources: ClusterResourceManager,
        url: str = None,
    ) -> None:
        self.broker = broker
        self.resources = resources
        self.url = url

    def start(self) -> None:
        self.resources.register_instance(
            InstanceState(self.broker.metrics.scope, role="broker", url=self.url)
        )
        self.resources.add_view_listener(self.on_view_change)
        # controller-declared liveness flips (heartbeat-miss -> dead,
        # re-registration -> alive) feed the broker's circuit breaker on
        # the same event that rebuilds routing — no polling race
        self.resources.add_instance_listener(self.on_instance_change)
        # seed routing for any pre-existing tables
        for table in self.resources.tables():
            self.on_view_change(table, self.resources.get_external_view(table))

    def on_instance_change(self, name: str, alive: bool) -> None:
        if alive:
            self.broker.health.mark_alive(name)
        else:
            self.broker.health.mark_dead(name)

    def on_view_change(self, table: str, view: Dict[str, Dict[str, str]]) -> None:
        if table not in self.resources.tables():
            self.broker.routing.remove(table)
            self.broker.time_boundary.remove(table)
            # clear the SLO override once no physical half of the raw
            # table remains (hybrid: OFFLINE and REALTIME share one)
            raw = table.rsplit("_", 1)[0]
            if not any(
                t.rsplit("_", 1)[0] == raw for t in self.resources.tables()
            ):
                self.broker.slo.set_objective(raw, None)
            return
        self.broker.routing.update(table, view)
        config = self.resources.table_configs.get(table)
        if config is not None:
            # idempotent for an unchanged quota (tokens preserved — a
            # view-change re-notify must not refill a drained bucket);
            # None clears the bucket when the quota was removed
            self.broker.quota.set_quota(
                config.raw_name,
                config.quota.max_queries_per_second,
                config.quota.burst_queries,
            )
            # per-table SLO objectives ride the same propagation path as
            # quotas (None clears back to the env defaults)
            self.broker.slo.set_objective(
                config.raw_name,
                config.slo.to_json() if config.slo is not None else None,
            )
            # declared partitioning feeds the join planner's colocation
            # check (broker/joinplan.py PartitionRegistry)
            p = config.partitioning
            self.broker.joinplan.partitions.set_partitioning(
                config.raw_name,
                p.column if p is not None else None,
                p.num_partitions if p is not None else None,
            )
        if table.endswith(OFFLINE_SUFFIX):
            metas = []
            for seg in self.resources.segments_of(table):
                info = self.resources.get_segment_metadata(table, seg)
                if info and info.get("metadata") is not None:
                    metas.append(info["metadata"])
            self.broker.time_boundary.update_from_segments(table, metas)
