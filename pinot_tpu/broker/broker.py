"""Broker: PQL front door — parse, route, scatter-gather, reduce.

The reference flow (``BrokerRequestHandler.java:139``): compile PQL ->
optimize -> look up routing table -> scatter InstanceRequests ->
gather DataTables (per-server errors become response exceptions, the
healthy partials still reduce, :443-460) -> BrokerReduceService ->
JSON.  Hybrid tables federate into offline+realtime sub-queries split
at the time boundary (:280-329; see ``pinot_tpu.broker.time_boundary``).

Scatter-gather fans out on a thread pool with a per-request timeout
(``ScatterGatherImpl.java:80``); replica choice already happened when
the routing table was built.
"""
from __future__ import annotations

import concurrent.futures
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from pinot_tpu.common.datatable import (
    deserialize_result,
    serialize_instance_request,
)
from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree, RangeSpec
from pinot_tpu.common.response import BrokerResponse, ErrorCode, QueryException
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.pql import PqlParseError, optimize_request, parse_pql
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.broker.time_boundary import TimeBoundaryService
from pinot_tpu.utils.metrics import BrokerMetrics

logger = logging.getLogger(__name__)

OFFLINE_SUFFIX = "_OFFLINE"
REALTIME_SUFFIX = "_REALTIME"


class BrokerRequestHandler:
    def __init__(
        self,
        transport,
        server_addresses: Dict[str, Tuple[str, int]],
        routing: Optional[RoutingTableProvider] = None,
        time_boundary: Optional[TimeBoundaryService] = None,
        timeout_ms: float = 15_000.0,
        name: str = "broker0",
    ) -> None:
        self.transport = transport
        self.server_addresses = dict(server_addresses)
        self.routing = routing or RoutingTableProvider()
        self.time_boundary = time_boundary or TimeBoundaryService()
        self.timeout_ms = timeout_ms
        self.metrics = BrokerMetrics(name)
        from pinot_tpu.broker.quota import QueryQuotaManager

        self.quota = QueryQuotaManager()
        self._request_id = 0
        self._id_lock = threading.Lock()
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)

    def set_server_address(self, server: str, address: Tuple[str, int]) -> None:
        self.server_addresses[server] = address

    def _next_id(self) -> int:
        with self._id_lock:
            self._request_id += 1
            return self._request_id

    # ------------------------------------------------------------------
    def handle_pql(
        self,
        pql: str,
        trace: bool = False,
        debug_options: Optional[Dict[str, str]] = None,
        timeout_ms: Optional[float] = None,
    ) -> BrokerResponse:
        t0 = time.perf_counter()
        self.metrics.meter("queries").mark()
        try:
            request = parse_pql(pql)
            if debug_options:
                request.debug_options = dict(debug_options)
            request = optimize_request(request)
        except PqlParseError as e:
            # InvalidQueryOptionsError subclasses this; internal
            # ValueErrors now propagate instead of masquerading as
            # client parse errors (ADVICE r1)
            resp = BrokerResponse(
                exceptions=[QueryException(ErrorCode.PQL_PARSING, str(e))]
            )
            resp.time_used_ms = (time.perf_counter() - t0) * 1000
            return resp
        request.enable_trace = trace
        resp = self.handle_request(request, pql, timeout_ms=timeout_ms)
        resp.time_used_ms = (time.perf_counter() - t0) * 1000
        self.metrics.timer("queryTotal").update(resp.time_used_ms)
        return resp

    def handle_request(
        self,
        request: BrokerRequest,
        pql: str,
        timeout_ms: Optional[float] = None,
    ) -> BrokerResponse:
        # per-query override (reference: timeoutMs request parameter,
        # InstanceRequest carries it); the broker's configured timeout
        # is the CEILING so a client can shorten but never extend
        if timeout_ms is not None and timeout_ms > 0:
            timeout_ms = min(float(timeout_ms), self.timeout_ms)
        else:
            timeout_ms = self.timeout_ms
        table = request.table_name
        if not self.quota.allow(table):
            self.metrics.meter("queriesDropped").mark()
            return BrokerResponse(
                exceptions=[
                    QueryException(
                        ErrorCode.TOO_MANY_REQUESTS,
                        f"query rate on table {table} exceeds the configured quota",
                    )
                ]
            )
        physical = self._physical_tables(table, pql)
        if not physical:
            return BrokerResponse(
                exceptions=[
                    QueryException(
                        ErrorCode.BROKER_RESOURCE_MISSING, f"no routing for table {table}"
                    )
                ]
            )

        parts: List[IntermediateResult] = []
        exceptions: List[QueryException] = []
        futures = []
        for phys_table, sub_pql in physical:
            routing = self.routing.find_servers(phys_table)
            if not routing:
                # None (table unknown) or {} (external view refilling
                # after a restart): either way this physical table is
                # currently unanswerable — surface a retriable error
                # rather than silently dropping it from the result
                exceptions.append(
                    QueryException(
                        ErrorCode.BROKER_RESOURCE_MISSING,
                        f"no servers currently serving table {phys_table}",
                    )
                )
                continue
            for server, segments in routing.items():
                futures.append(
                    (
                        server,
                        self._pool.submit(
                            self._send_one,
                            server,
                            phys_table,
                            sub_pql,
                            segments,
                            request.enable_trace,
                            request.debug_options or None,
                            timeout_ms,
                        ),
                    )
                )

        t_sg = time.perf_counter()
        deadline = t_sg + timeout_ms / 1000.0
        for server, fut in futures:
            try:
                # no per-future floor: once the shared deadline passes,
                # remaining futures fail immediately instead of each
                # adding another grace period to a short budget
                remaining = max(0.0, deadline - time.perf_counter())
                parts.append(fut.result(timeout=remaining))
            except Exception as e:
                # free queued zombies: a not-yet-started scatter task
                # whose result nobody will read shouldn't occupy a pool
                # worker (no-op for already-running tasks)
                fut.cancel()
                logger.warning("server %s failed: %s", server, e)
                exceptions.append(
                    QueryException(
                        ErrorCode.BROKER_GATHER, f"server {server}: {type(e).__name__}: {e}"
                    )
                )
        self.metrics.timer("scatterGather").update((time.perf_counter() - t_sg) * 1000)

        t_red = time.perf_counter()
        for p in parts:
            for code, msg in p.exceptions:
                exceptions.append(QueryException(code, msg))
        resp = reduce_to_response(request, parts, exceptions)
        self.metrics.timer("reduce").update((time.perf_counter() - t_red) * 1000)
        resp.num_servers_queried = len(futures)
        resp.num_servers_responded = len(parts)
        return resp

    # ------------------------------------------------------------------
    def _physical_tables(self, table: str, pql: str) -> List[Tuple[str, str]]:
        """Logical table -> [(physical table, sub-query pql)].

        Hybrid federation (BrokerRequestHandler.java:280-329): a table
        with both OFFLINE and REALTIME physical tables gets the query
        duplicated with a time-boundary filter added on each side.
        """
        known = set(self.routing.tables())
        if table in known:
            return [(table, pql)]
        offline = table + OFFLINE_SUFFIX
        realtime = table + REALTIME_SUFFIX
        if offline in known and realtime in known:
            boundary = self.time_boundary.get(offline)
            if boundary is not None:
                col, value = boundary
                return [
                    (offline, self._with_time_filter(pql, col, value, is_offline=True)),
                    (realtime, self._with_time_filter(pql, col, value, is_offline=False)),
                ]
            return [(offline, pql)]
        if offline in known:
            return [(offline, pql)]
        if realtime in known:
            return [(realtime, pql)]
        return []

    def _with_time_filter(self, pql: str, col: str, value: int, is_offline: bool) -> str:
        """Append the hybrid time-boundary predicate to the PQL text
        (offline: col <= boundary; realtime: col > boundary —
        HelixExternalViewBasedTimeBoundaryService.java:52-85)."""
        op = "<=" if is_offline else ">"
        upper = pql.upper()
        pred = f"{col} {op} {value}"
        if " WHERE " in upper:
            idx = upper.index(" WHERE ") + len(" WHERE ")
            rest = pql[idx:]
            # predicate list ends at the next clause keyword
            end = len(rest)
            for kw in (" GROUP BY ", " ORDER BY ", " HAVING ", " TOP ", " LIMIT "):
                j = rest.upper().find(kw)
                if j != -1:
                    end = min(end, j)
            return pql[:idx] + f"({rest[:end]}) AND {pred}" + rest[end:]
        # insert WHERE after FROM <table>
        ufrom = upper.index(" FROM ")
        after = pql[ufrom + len(" FROM ") :]
        stop = len(after)
        for kw in (" WHERE ", " GROUP BY ", " ORDER BY ", " HAVING ", " TOP ", " LIMIT "):
            j = after.upper().find(kw)
            if j != -1:
                stop = min(stop, j)
        return (
            pql[: ufrom + len(" FROM ")] + after[:stop] + f" WHERE {pred}" + after[stop:]
        )

    def _send_one(
        self,
        server: str,
        table: str,
        pql: str,
        segments: List[str],
        trace: bool,
        debug_options: Optional[Dict[str, str]],
        timeout_ms: float,
    ) -> IntermediateResult:
        # timeout_ms arrives already clamped by handle_request — the
        # one place the "shorten but never extend" ceiling lives
        address = self.server_addresses[server]
        payload = serialize_instance_request(
            self._next_id(),
            pql,
            table,
            segments,
            timeout_ms,
            trace=trace,
            debug_options=debug_options,
        )
        reply = self.transport.request(address, payload, timeout=timeout_ms / 1000.0)
        return deserialize_result(reply)


# ---------------------------------------------------------------------------
# HTTP front (PinotClientRequestServlet analog)
# ---------------------------------------------------------------------------


def _parse_timeout(v) -> Optional[float]:
    """Lenient per-query timeoutMs: numbers/number-strings pass, junk
    is ignored (never crash a query over a malformed option)."""
    if isinstance(v, bool):  # float(True) == 1.0 — a flag is junk here
        return None
    try:
        t = float(v)
        return t if t > 0 else None
    except (TypeError, ValueError):
        return None


def _parse_debug_options(s: str) -> Optional[Dict[str, str]]:
    """``"k=v;k2=v2"`` -> dict (the reference's semicolon/equals debug
    option string, ``BrokerRequestHandler.java:156-159``)."""
    if not s:
        return None
    out: Dict[str, str] = {}
    for part in s.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out or None


class BrokerHttpServer:
    """HTTP endpoint: GET /query?pql=... and POST /query {"pql": ...}
    (``PinotClientRequestServlet.java:54/:73``)."""

    def __init__(self, handler: BrokerRequestHandler, host: str = "127.0.0.1", port: int = 0):
        broker = handler

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _respond(self, payload: Dict[str, Any], status: int = 200) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path not in ("/query", "/"):
                    if url.path == "/health":
                        return self._respond({"status": "ok"})
                    if url.path == "/metrics":
                        return self._respond(broker.metrics.snapshot())
                    return self._respond({"error": "not found"}, 404)
                qs = parse_qs(url.query)
                pql = (qs.get("pql") or qs.get("bql") or [""])[0]
                trace = (qs.get("trace") or ["false"])[0].lower() == "true"
                debug = _parse_debug_options((qs.get("debugOptions") or [""])[0])
                resp = broker.handle_pql(
                    pql,
                    trace=trace,
                    debug_options=debug,
                    timeout_ms=_parse_timeout((qs.get("timeoutMs") or [""])[0]),
                )
                self._respond(resp.to_json())

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError as e:
                    return self._respond(
                        {"exceptions": [{"errorCode": ErrorCode.JSON_PARSING, "message": str(e)}]}
                    )
                pql = body.get("pql") or body.get("bql") or ""
                debug = body.get("debugOptions") or ""
                if isinstance(debug, dict):
                    debug = {str(k): str(v) for k, v in debug.items()}
                else:
                    # the reference's "k=v;k2=v2" string form; any other
                    # JSON type is ignored rather than crashing the handler
                    debug = _parse_debug_options(debug if isinstance(debug, str) else "")
                resp = broker.handle_pql(
                    pql,
                    trace=bool(body.get("trace")),
                    debug_options=debug,
                    timeout_ms=_parse_timeout(body.get("timeoutMs")),
                )
                self._respond(resp.to_json())

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
