"""Broker: PQL front door — parse, route, scatter-gather, reduce.

The reference flow (``BrokerRequestHandler.java:139``): compile PQL ->
optimize -> look up routing table -> scatter InstanceRequests ->
gather DataTables (per-server errors become response exceptions, the
healthy partials still reduce, :443-460) -> BrokerReduceService ->
JSON.  Hybrid tables federate into offline+realtime sub-queries split
at the time boundary (:280-329; see ``pinot_tpu.broker.time_boundary``).

Scatter-gather fans out on a thread pool with a per-request timeout
(``ScatterGatherImpl.java:80``); replica choice already happened when
the routing table was built.

RESILIENCE LAYER (beyond the reference, which degrades a query on any
server failure): the gather loop is an event loop over attempt futures
that (a) **fails over** — a transport error, per-attempt timeout, or
retryable server error (210 saturated / 220 shutting down) re-issues
the failed attempt's segment set to an alternate replica with capped
exponential backoff, under the query's total deadline; (b) **hedges** —
when enabled, a straggling attempt's segment set is speculatively
re-sent to a second replica after a percentile-based delay and the
first reply wins; (c) feeds a per-server **circuit breaker**
(``broker.health``) consulted by routing so repeat offenders drop out
of covers before they fail queries; (d) propagates the **remaining**
deadline into every (re-)issued InstanceRequest so servers shed work
the broker has already given up on; and (e) reports **graceful
degradation** honestly — segments still unserved after retries flip
``partialResponse`` and count into ``numSegmentsUnserved`` instead of
hiding inside exception strings.
"""
from __future__ import annotations

import concurrent.futures
import json
import logging
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set, Tuple
from urllib.parse import parse_qs, urlparse

from pinot_tpu.common.datatable import (
    deserialize_result,
    serialize_instance_request,
)
from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree, RangeSpec
from pinot_tpu.common.response import BrokerResponse, ErrorCode, QueryException
from pinot_tpu.engine.reduce import reduce_to_response
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.pql import PqlParseError, optimize_request, parse_pql
from pinot_tpu.broker.health import ServerHealthTracker
from pinot_tpu.broker.querylog import SlowQueryLog
from pinot_tpu.broker.routing import RoutingTableProvider
from pinot_tpu.broker.time_boundary import TimeBoundaryService
from pinot_tpu.utils.metrics import BrokerMetrics, prometheus_text
from pinot_tpu.utils.trace import NULL_TRACE, TraceContext, merge_scope

logger = logging.getLogger(__name__)

OFFLINE_SUFFIX = "_OFFLINE"
REALTIME_SUFFIX = "_REALTIME"

# server-reply error codes that mean "this replica cannot serve right
# now, another may" — the attempt fails over instead of degrading the
# query (fatal codes like QUERY_EXECUTION would fail identically on
# every replica and do not retry)
RETRYABLE_SERVER_CODES = frozenset(
    {
        ErrorCode.SERVER_SCHEDULER_DOWN,
        ErrorCode.SERVER_SHUTTING_DOWN,
        # "I don't hold the segments this request names" (e.g. a
        # colocated-join build side that moved): a replica may hold
        # them locally, so the broker re-covers there before degrading
        ErrorCode.SERVER_SEGMENT_MISSING,
    }
)


class _Batch:
    """One segment set bound for one server: the unit of scatter,
    failover, and hedging.  A failover spawns child batches (possibly
    splitting segments across replicas); the parent is then superseded."""

    __slots__ = (
        "table", "pql", "segments", "server", "excluded",
        "reissues", "errors", "done", "inflight",
        "hedged", "first_sent", "order",
    )

    # NOTE: join-phase context rides per-submit via _scatter_gather's
    # ``extra_fn(server)`` — derived from the target server at send
    # time so failover children automatically get the right build
    # segment list for THEIR server (broker/joinplan.py)

    def __init__(
        self,
        table: str,
        pql: str,
        segments: List[str],
        server: str,
        excluded: Optional[Set[str]] = None,
        reissues: int = 0,
        errors: Optional[List[QueryException]] = None,
        order: int = 0,
    ) -> None:
        self.table = table
        self.pql = pql
        self.segments = list(segments)
        self.server = server
        self.order = order
        self.excluded: Set[str] = set(excluded or ()) | {server}
        self.reissues = reissues
        self.errors: List[QueryException] = list(errors or ())
        self.done = False
        self.inflight = 0
        self.hedged = False
        self.first_sent = 0.0


class BrokerRequestHandler:
    def __init__(
        self,
        transport,
        server_addresses: Dict[str, Tuple[str, int]],
        routing: Optional[RoutingTableProvider] = None,
        time_boundary: Optional[TimeBoundaryService] = None,
        timeout_ms: float = 15_000.0,
        name: str = "broker0",
        retry_attempts: int = 2,
        retry_backoff_ms: float = 25.0,
        retry_backoff_cap_ms: float = 1_000.0,
        hedge_delay_ms: float = 0.0,
        hedge_latency_percentile: float = 95.0,
        hedge_min_quota_headroom: float = 0.1,
        health: Optional[ServerHealthTracker] = None,
        max_inflight_per_table: Optional[int] = None,
        admission_window_init: Optional[float] = None,
        admission_window_max: Optional[float] = None,
        admission_pending_high_water: Optional[float] = None,
    ) -> None:
        self.transport = transport
        self.server_addresses = dict(server_addresses)
        self.routing = routing or RoutingTableProvider()
        self.time_boundary = time_boundary or TimeBoundaryService()
        self.timeout_ms = timeout_ms
        self.name = name
        self.metrics = BrokerMetrics(name)
        self.querylog = SlowQueryLog()
        self.retry_attempts = max(0, retry_attempts)
        self.retry_backoff_ms = retry_backoff_ms
        self.retry_backoff_cap_ms = retry_backoff_cap_ms
        self.hedge_delay_ms = hedge_delay_ms  # 0 disables hedging
        self.hedge_latency_percentile = hedge_latency_percentile
        self.hedge_min_quota_headroom = hedge_min_quota_headroom
        self.health = health or ServerHealthTracker()
        # controller-declared draining servers (deliberate decommission,
        # NOT failures): routing views already exclude them; kept here so
        # /serverhealth can tell an operator drain from a sick circuit
        self.draining_servers: Set[str] = set()
        from pinot_tpu.broker.admission import AdmissionController
        from pinot_tpu.broker.quota import QueryQuotaManager

        self.quota = QueryQuotaManager()
        # adaptive admission: QPS bucket + per-table in-flight cap +
        # AIMD per-server windows fed by reply backpressure snapshots
        # (broker/admission.py) — ONE front door for every shed tier
        self.admission = AdmissionController(
            quota=self.quota,
            max_inflight_per_table=max_inflight_per_table,
            initial_window=admission_window_init,
            max_window=admission_window_max,
            pending_high_water=admission_pending_high_water,
            metrics=self.metrics,
        )
        self._request_id = 0
        self._id_lock = threading.Lock()
        # globally-unique request ids: broker name + a process-unique
        # token (two brokers sharing a default name, or one restarting,
        # can never reuse an id) + a per-broker sequence
        import uuid

        self._id_prefix = f"{name}-{uuid.uuid4().hex[:6]}"
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)
        # cost-accounting plane: broker-side totals of the merged per-
        # query cost vector, pre-registered so /metrics shows zeros
        # before first use (per-table table.<name>.* twins register on
        # the first query that names the table)
        for m in ("cost.docsScanned", "cost.bytesScanned"):
            self.metrics.meter(m)
        for t in ("cost.deviceMs", "cost.hostMs"):
            self.metrics.timer(t)
        # workload-introspection plane: per-plan-digest roll-up of every
        # merged response (utils/planstats.py) behind /debug/workload —
        # top-K by frequency and by cost, the "which plan shapes should
        # we batch?" answer.  Series pre-registered.
        from pinot_tpu.utils.planstats import PlanStatsStore

        self.planstats = PlanStatsStore()
        for m in ("workload.recorded", "explain.queries"):
            self.metrics.meter(m)
        self.metrics.gauge("workload.digests").set_fn(self.planstats.digest_count)
        # distributed join plane (broker/joinplan.py): strategy planner
        # + multi-phase exchange coordinator; registers its join.*
        # meters at construction
        from pinot_tpu.broker.joinplan import JoinCoordinator

        self.joinplan = JoinCoordinator(self)
        # SLO & tail-latency attribution plane (ISSUE 11): ONE history
        # thread snapshots this registry (+ the per-table SLO counters)
        # on a cadence; burn-rate evaluation and the flight-recorder
        # triggers ride its tick hook.  Tail sampling arms lightweight
        # tracing on EVERY query and keeps the merged span tree only
        # for slow/failed/partial/1-in-N completions (utils/tailsample).
        # All series pre-registered inside the constructors.
        from pinot_tpu.utils.flightrec import FlightRecorder
        from pinot_tpu.utils.slo import SloTracker
        from pinot_tpu.utils.tailsample import TailSampler
        from pinot_tpu.utils.timeseries import HistoryRecorder

        self.history = HistoryRecorder(self.metrics, metrics=self.metrics)
        self.slo = SloTracker(history=self.history, metrics=self.metrics)
        self.history.register_provider(self.slo.series)
        self.tail = TailSampler(metrics=self.metrics)
        self.flightrec = FlightRecorder(
            "broker",
            name,
            metrics=self.metrics,
            sources={
                "history": lambda: self.history.query(window_s=900),
                "slowQueries": self.querylog.snapshot,
                "tails": lambda: self.tail.snapshot(include_traces=True),
                "slo": self.slo.snapshot,
                "workload": lambda: self.workload_snapshot(top=20),
                "admission": self.admission.snapshot,
                # lazy: the replica auditor is constructed just below
                "audit": lambda: self.replica_audit.snapshot(),
            },
        )
        # correctness & freshness audit plane (ISSUE 19): background
        # replica divergence sampler (utils/audit.py, always-on unless
        # PINOT_TPU_AUDIT_REPLICA_N=0) + the event-time freshness
        # timer, pre-registered so /metrics shows the series at zero
        from pinot_tpu.utils.audit import ReplicaAuditor

        self.replica_audit = ReplicaAuditor(self)
        self.metrics.timer("freshness.lagMs")
        self._last_dropped = 0
        self._shed_burst_threshold = max(
            1, int(os.environ.get("PINOT_TPU_FLIGHTREC_SHED_BURST", "32"))
        )
        self.history.add_tick_hook(self._history_tick)

    @classmethod
    def from_conf(cls, transport, server_addresses, conf, **overrides) -> "BrokerRequestHandler":
        """Build a handler from a ``BrokerConf`` (pinot.broker.* keys),
        mapping the resilience knobs onto the scatter-gather layer."""
        kwargs = dict(
            timeout_ms=float(conf.timeout_ms),
            name=conf.instance_id,
            routing=RoutingTableProvider(num_tables=conf.routing_table_count),
            retry_attempts=conf.retry_attempts,
            retry_backoff_ms=conf.retry_backoff_ms,
            retry_backoff_cap_ms=conf.retry_backoff_cap_ms,
            hedge_delay_ms=conf.hedge_delay_ms,
            hedge_latency_percentile=conf.hedge_latency_percentile,
            hedge_min_quota_headroom=conf.hedge_min_quota_headroom,
            health=ServerHealthTracker(
                failure_threshold=conf.health_failure_threshold,
                penalty_ms=conf.health_penalty_ms,
            ),
            max_inflight_per_table=conf.admission_table_inflight,
            admission_window_init=conf.admission_window_init,
            admission_window_max=conf.admission_window_max,
            admission_pending_high_water=conf.admission_pending_high_water,
        )
        kwargs.update(overrides)
        return cls(transport, server_addresses, **kwargs)

    def set_server_address(self, server: str, address: Tuple[str, int]) -> None:
        self.server_addresses[server] = address

    def _next_request_id(self) -> str:
        with self._id_lock:
            self._request_id += 1
            n = self._request_id
        return f"{self._id_prefix}-{n}"

    # ------------------------------------------------------------------
    def handle_pql(
        self,
        pql: str,
        trace: bool = False,
        debug_options: Optional[Dict[str, str]] = None,
        timeout_ms: Optional[float] = None,
    ) -> BrokerResponse:
        t0 = time.perf_counter()
        self.metrics.meter("queries").mark()
        request_id = self._next_request_id()
        # with the tail sampler armed (default), EVERY query carries the
        # lightweight span tree so the retention decision can happen at
        # completion; with sampling off (PINOT_TPU_TAIL_TRACE=0),
        # untraced queries share the NULL context — no span allocation
        # anywhere on the handle path (the PR 4 zero-overhead contract)
        ctx = (
            TraceContext(enabled=True, scope=self.name, trace_id=request_id)
            if trace or self.tail.armed
            else NULL_TRACE
        )
        resp: Optional[BrokerResponse] = None
        request = None
        plan_digest = ""
        plan_summary = ""
        with ctx.span("query", requestId=request_id, pql=pql[:200]):
            t_parse = time.perf_counter()
            try:
                with ctx.span("parse"):
                    request = parse_pql(pql)
                    if debug_options:
                        request.debug_options = dict(debug_options)
                    request = optimize_request(request)
                from pinot_tpu.engine.plandigest import (
                    plan_shape_digest,
                    plan_shape_summary,
                )

                # the literal-erased shape digest rides EVERY response
                # (cross-links /debug/queries -> /debug/plans/workload)
                plan_digest = plan_shape_digest(request)
                plan_summary = plan_shape_summary(request)
                if request.explain:
                    self.metrics.meter("explain.queries").mark()
            except PqlParseError as e:
                # InvalidQueryOptionsError subclasses this; internal
                # ValueErrors now propagate instead of masquerading as
                # client parse errors (ADVICE r1)
                resp = BrokerResponse(
                    exceptions=[QueryException(ErrorCode.PQL_PARSING, str(e))]
                )
            parse_ms = (time.perf_counter() - t_parse) * 1000
            self.metrics.timer("phase.parse").update(parse_ms)
            if resp is None:
                request.enable_trace = ctx.enabled
                resp = self.handle_request(
                    request,
                    pql,
                    timeout_ms=timeout_ms,
                    request_id=request_id,
                    trace_ctx=ctx,
                )
        if not trace and resp.trace_info:
            # tail arming traces every query internally, but the client
            # contract is unchanged: traceInfo rides the response only
            # when the caller asked (trace=true).  The armed span trees
            # reach the tail sampler via the _server_traces side channel
            # below, never an untraced client's payload (which must stay
            # byte-identical to the sampling-off response).
            resp.trace_info = {}
        resp.request_id = request_id
        resp.time_used_ms = (time.perf_counter() - t0) * 1000
        self.metrics.timer("queryTotal").update(resp.time_used_ms)
        shed_q = any(
            e.error_code == ErrorCode.TOO_MANY_REQUESTS
            for e in resp.exceptions
        )
        if plan_digest:
            resp.plan_digest = plan_digest
            if request is None or request.explain != "plan":
                # workload roll-up: every executed query lands in the
                # per-digest registry (plain EXPLAIN excluded — it did
                # no work and must not skew frequency/cost rankings)
                self.planstats.record(
                    plan_digest,
                    summary=plan_summary,
                    table=getattr(request, "table_name", "") or "",
                    latency_ms=resp.time_used_ms,
                    cost=resp.cost,
                    num_docs=resp.num_docs_scanned,
                    shed=shed_q,
                    failed=bool(resp.exceptions) and not shed_q,
                    pql=pql,
                )
                self.metrics.meter("workload.recorded").mark()
        failed_q = bool(resp.exceptions)
        tail_reason = None
        if ctx.enabled:

            def _build_scopes() -> Dict[str, Any]:
                # merge the per-server span trees under their scatter
                # attempts, next to this broker's own tree — ONE
                # waterfall.  Deliberately deferred: on the tail
                # sampler's NOT-retained path this merge (and its span
                # copies) never runs — the zero-overhead contract.
                scopes: Dict[str, Any] = {}
                merge_scope(scopes, ctx.to_dict())
                for attempt_id, server_trace in (
                    getattr(resp, "_server_traces", ()) or ()
                ):
                    merge_scope(scopes, server_trace, root_parent=attempt_id)
                return scopes

            built: Optional[Dict[str, Any]] = None
            if trace:
                built = _build_scopes()
                resp.trace_info = {"traceId": request_id, "scopes": built}
            if self.tail.armed:
                scopes_fn = (lambda b=built: b) if built is not None else _build_scopes
                # sheds are typed overload verdicts, not failures worth a
                # span tree: retaining them would do the MOST tail work
                # exactly during a 429 storm (and flood the bounded ring
                # with microsecond entries), inverting the zero-overhead
                # contract.  SLO availability still counts them below.
                tail_reason = self.tail.observe(
                    request_id,
                    resp.time_used_ms,
                    failed_q and not shed_q,
                    resp.partial_response,
                    scopes_fn,
                    table=getattr(request, "table_name", "") or "",
                    plan_digest=plan_digest,
                    summary=plan_summary,
                )
        # per-table SLO counters (utils/slo.py): burn rates evaluate on
        # the history cadence over exactly these cumulative series
        self.slo.observe(
            getattr(request, "table_name", "") or "",
            resp.time_used_ms,
            failed_q,
            freshness_ms=resp.freshness_ms,
        )
        phases = dict(getattr(resp, "phase_ms", ()) or ())
        phases["parse"] = round(parse_ms, 3)
        if self.querylog.observe(
            {
                # tail cross-link: the retained span tree is fetchable by
                # this requestId (both directions: /debug/tails entries
                # carry the requestId back into this log)
                "traceRetained": bool(tail_reason),
                **(
                    {"traceRef": f"/debug/tails?requestId={request_id}"}
                    if tail_reason
                    else {}
                ),
                "requestId": request_id,
                "pql": pql[:500],
                # cross-link key into /debug/plans and /debug/workload
                "planDigest": plan_digest,
                "table": getattr(request, "table_name", None),
                "timeUsedMs": round(resp.time_used_ms, 3),
                # event-time staleness of the served answer (None for
                # offline-only queries): the /debug/queries twin of the
                # response's freshnessMs
                "freshnessMs": (
                    round(resp.freshness_ms, 3)
                    if resp.freshness_ms is not None
                    else None
                ),
                "phasesMs": phases,
                # the merged cost vector: "why was this slow" answerable
                # from the log entry alone (rows/bytes, device vs host)
                "numDocsScanned": resp.num_docs_scanned,
                "cost": {
                    k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in sorted(resp.cost.items())
                },
                "partialResponse": resp.partial_response,
                "numServersQueried": resp.num_servers_queried,
                "numServersResponded": resp.num_servers_responded,
                "numSegmentsUnserved": resp.num_segments_unserved,
                "numRetries": resp.num_retries,
                "numHedges": resp.num_hedges,
                "exceptions": [e.error_code for e in resp.exceptions],
                "traced": trace,
            }
        ):
            self.metrics.meter("slowQueries").mark()
        if failed_q and any(
            e.error_code
            not in (ErrorCode.TOO_MANY_REQUESTS, ErrorCode.PQL_PARSING)
            for e in resp.exceptions
        ):
            # notable event: a genuinely failed query (sheds are typed
            # overload verdicts, parse errors are client bugs) dumps the
            # observability state that explains it — rate-limited and
            # disabled unless PINOT_TPU_FLIGHTREC_DIR is set
            self.flightrec.maybe_dump(
                "failedQuery",
                {
                    "requestId": request_id,
                    "table": getattr(request, "table_name", None),
                    "codes": [e.error_code for e in resp.exceptions],
                },
            )
        return resp

    def _history_tick(self, now: float) -> None:
        """Runs on every history sample (the recorder's cadence): SLO
        burn evaluation + the broker-side flight-recorder triggers."""
        ev = self.slo.evaluate()
        for table in ev.get("crossed", ()):
            t = ev["tables"].get(table, {})
            self.flightrec.maybe_dump(
                "sloBurn",
                {
                    "table": table,
                    "burnRate5m": t.get("burnRate5m"),
                    "burnRate1h": t.get("burnRate1h"),
                },
            )
        dropped = self.metrics.meter("queriesDropped").count
        delta = dropped - self._last_dropped
        self._last_dropped = dropped
        if delta >= self._shed_burst_threshold:
            self.flightrec.maybe_dump("shedBurst", {"droppedThisTick": delta})

    def shutdown(self) -> None:
        """Stop the history recorder thread (idempotent); the scatter
        pool's daemon workers die with the process as before."""
        self.replica_audit.stop()
        self.history.stop()

    def handle_request(
        self,
        request: BrokerRequest,
        pql: str,
        timeout_ms: Optional[float] = None,
        request_id: Optional[str] = None,
        trace_ctx: Optional[TraceContext] = None,
    ) -> BrokerResponse:
        ctx = trace_ctx if trace_ctx is not None else NULL_TRACE
        if request_id is None:
            request_id = self._next_request_id()
        # per-query override (reference: timeoutMs request parameter,
        # InstanceRequest carries it); the broker's configured timeout
        # is the CEILING so a client can shorten but never extend.  A
        # present-but-invalid override is a client error, not something
        # to silently replace with the default — same contract as the
        # HTTP layer (ONE validator: _parse_timeout).
        try:
            timeout_ms = _parse_timeout(timeout_ms)
        except InvalidTimeoutError as e:
            return BrokerResponse(
                exceptions=[QueryException(ErrorCode.QUERY_VALIDATION, str(e))],
                request_id=request_id,
            )
        timeout_ms = (
            self.timeout_ms if timeout_ms is None else min(timeout_ms, self.timeout_ms)
        )
        table = request.table_name
        # adaptive admission front door: QPS bucket + per-table
        # in-flight cap — both shed with a typed 429 naming the tier
        decision = self.admission.try_admit(table)
        if not decision.admitted:
            self.metrics.meter("queriesDropped").mark()
            return BrokerResponse(
                exceptions=[
                    QueryException(ErrorCode.TOO_MANY_REQUESTS, decision.message)
                ],
                request_id=request_id,
            )
        try:
            return self._handle_admitted(
                request, pql, timeout_ms, request_id, ctx, table
            )
        finally:
            # the in-flight slot frees when the query leaves the broker,
            # whatever path it took out
            self.admission.release(table)

    def _handle_admitted(
        self,
        request: BrokerRequest,
        pql: str,
        timeout_ms: float,
        request_id: str,
        ctx: TraceContext,
        table: str,
    ) -> BrokerResponse:
        if request.join is not None:
            # broker-planned distributed join (broker/joinplan.py):
            # strategy choice + multi-phase scatter, riding the same
            # resilient scatter-gather machinery per phase.  Admission
            # already happened (the left table's quota/in-flight slot).
            with ctx.span("joinPlan", table=table):
                resp = self.joinplan.handle(
                    request, pql, timeout_ms, request_id, ctx, table
                )
            resp.request_id = request_id
            resp._server_traces = getattr(resp, "_server_traces", [])
            return resp
        t_route = time.perf_counter()
        try:
            with ctx.span("route", table=table):
                physical = self._physical_tables(table, pql)
                if not physical:
                    return BrokerResponse(
                        exceptions=[
                            QueryException(
                                ErrorCode.BROKER_RESOURCE_MISSING, f"no routing for table {table}"
                            )
                        ],
                        request_id=request_id,
                    )

                exceptions: List[QueryException] = []
                batches: List[_Batch] = []
                routing_gap = False
                for phys_table, sub_pql in physical:
                    routing = self.routing.find_servers(phys_table, health=self.health)
                    if not routing:
                        # None (table unknown) or {} (external view refilling
                        # after a restart): either way this physical table is
                        # currently unanswerable — surface a retriable error
                        # rather than silently dropping it from the result
                        routing_gap = True
                        exceptions.append(
                            QueryException(
                                ErrorCode.BROKER_RESOURCE_MISSING,
                                f"no servers currently serving table {phys_table}",
                            )
                        )
                        continue
                    for server, segments in routing.items():
                        batches.append(
                            _Batch(phys_table, sub_pql, segments, server, order=len(batches))
                        )
        finally:
            # timed even on the no-routing return: a silent phase.route
            # series during an external-view refill would hide exactly
            # the period when route behavior changed
            self.metrics.timer("phase.route").update(
                (time.perf_counter() - t_route) * 1000
            )

        # AIMD pre-scatter overload check: when EVERY server covering the
        # table is past its congestion window, scattering could only end
        # in 210s or timeouts — shed here, at the cheapest tier (429)
        if batches:
            cover = self.admission.check_cover(
                table, sorted({b.server for b in batches})
            )
            if not cover.admitted:
                self.metrics.meter("queriesDropped").mark()
                return BrokerResponse(
                    exceptions=exceptions
                    + [QueryException(ErrorCode.TOO_MANY_REQUESTS, cover.message)],
                    request_id=request_id,
                )

        t_sg = time.perf_counter()
        with ctx.span("scatterGather", batches=len(batches)):
            parts, sg = self._scatter_gather(
                request, batches, timeout_ms, table, request_id, ctx
            )
        exceptions.extend(sg["exceptions"])
        sg_ms = (time.perf_counter() - t_sg) * 1000
        self.metrics.timer("scatterGather").update(sg_ms)

        t_red = time.perf_counter()
        for p in parts:
            for code, msg in p.exceptions:
                exceptions.append(QueryException(code, msg))
        # plan nodes collected BEFORE reduce: the merge below folds
        # parts in place, and per-server attribution must survive it
        plan_nodes = (
            [n for p in parts for n in (p.plan_info or [])]
            if request.explain
            else []
        )
        if request.explain == "plan":
            # EXPLAIN returns the plan INSTEAD of results: nothing to
            # reduce (servers executed nothing, partials are empty)
            resp = BrokerResponse(exceptions=exceptions)
        else:
            with ctx.span("reduce", parts=len(parts)):
                resp = reduce_to_response(request, parts, exceptions)
        red_ms = (time.perf_counter() - t_red) * 1000
        self.metrics.timer("reduce").update(red_ms)
        resp.request_id = request_id
        # event-time freshness: now − the stalest realtime watermark
        # that contributed to this answer (server stamps min-combine
        # across the gather; broker derives the client-visible lag).
        # Offline-only answers have no stamped part and keep the key
        # absent — byte-identical to the pre-audit-plane payload.
        fmins = [
            p.freshness["minEventMs"]
            for p in parts
            if getattr(p, "freshness", None) is not None
            and p.freshness.get("minEventMs") is not None
        ]
        if fmins:
            from pinot_tpu.broker.freshness import now_ms

            resp.freshness_ms = max(0.0, now_ms() - min(fmins))
            self.metrics.timer("freshness.lagMs").update(resp.freshness_ms)
            self.metrics.gauge(f"freshness.{table}.lagMs").set(
                round(resp.freshness_ms, 3)
            )
        if request.explain:
            resp.explain = self._assemble_explain(request, plan_nodes, resp)
        # per-table cost attribution into the metrics registry: who is
        # burning the cluster, by logical table (rendered cluster-wide
        # on the controller's /debug/capacity rollup)
        self.metrics.meter("cost.docsScanned").mark(int(resp.num_docs_scanned))
        self.metrics.meter("cost.bytesScanned").mark(
            int(resp.cost.get("bytesScanned", 0))
        )
        self.metrics.meter(f"table.{table}.docsScanned").mark(
            int(resp.num_docs_scanned)
        )
        self.metrics.meter(f"table.{table}.bytesScanned").mark(
            int(resp.cost.get("bytesScanned", 0))
        )
        for key, timer in (("deviceMs", "cost.deviceMs"), ("hostMs", "cost.hostMs")):
            ms = resp.cost.get(key)
            if ms:
                self.metrics.timer(timer).update(float(ms))
        # the join planner's size estimator learns table totals from
        # every plain scan's merged reply (EXPLAIN of a join can then
        # name the strategy real execution will pick)
        if resp.total_docs:
            self.joinplan.stats.observe(table, resp.total_docs)
        resp.num_servers_queried = len(sg["servers_queried"])
        resp.num_servers_responded = len(sg["servers_responded"])
        resp.num_segments_unserved = len(sg["unserved"])
        resp.partial_response = bool(sg["unserved"]) or routing_gap
        resp.num_retries = sg["retries"]
        resp.num_hedges = sg["hedges"]
        # side-channel for handle_pql: per-server trace trees keyed by
        # the attempt span that carried them + the phase breakdown the
        # slow-query log records (not serialized into the response)
        resp._server_traces = sg["server_traces"]
        resp.phase_ms = {
            "scatterGather": round(sg_ms, 3),
            "reduce": round(red_ms, 3),
        }
        # replica-divergence sampling hook (utils/audit.py): a cheap
        # counter for the non-sampled majority, a bounded background
        # re-issue for the winners
        self.replica_audit.offer(request, batches, request_id, timeout_ms, resp)
        return resp

    def _assemble_explain(
        self,
        request: BrokerRequest,
        nodes: List[Dict[str, Any]],
        resp: BrokerResponse,
    ) -> Dict[str, Any]:
        """Broker-side EXPLAIN tree: the per-server plan nodes under one
        roof, with summed tier counts and estimates.  For ANALYZE the
        top level carries the merged actuals (== BrokerResponse.cost,
        exactly: only merged replies' nodes reach here)."""
        from pinot_tpu.engine.plandigest import (
            plan_shape_digest,
            plan_shape_summary,
        )

        tier_counts: Dict[str, int] = {}
        est_bytes = 0.0
        for n in nodes:
            for k, v in (n.get("tierCounts") or {}).items():
                tier_counts[k] = tier_counts.get(k, 0) + int(v)
            est = n.get("estimatedCost") or {}
            if est.get("source") == "history":
                est_bytes += float((est.get("perQuery") or {}).get("bytesScanned", 0))
            else:
                est_bytes += float(est.get("bytesScanned", 0))
        out: Dict[str, Any] = {
            "mode": request.explain,
            "planDigest": plan_shape_digest(request),
            "summary": plan_shape_summary(request),
            "numServers": len(nodes),
            "tierCounts": tier_counts,
            "estimatedCost": {"bytesScanned": int(est_bytes)},
            "servers": nodes,
        }
        if request.explain == "analyze":
            out["actualCost"] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in sorted(resp.cost.items())
            }
            out["actualDocsScanned"] = resp.num_docs_scanned
            if resp.freshness_ms is not None:
                out["freshnessMs"] = round(resp.freshness_ms, 3)
        return out

    def workload_snapshot(self, top: int = 20, tables=None) -> Dict[str, Any]:
        """``/debug/workload``: the per-plan-digest roll-up, top-K by
        frequency AND by total cost (the batching-candidate ranking).
        ``top`` at the registry capacity returns the FULL registry —
        the controller's fleet roll-up fetches that so cross-broker
        merging never ranks on truncated slices.  ``tables`` narrows
        the ranking to shapes touching those tables so a prewarming
        server only pulls plans it can actually stage."""
        return {
            "digests": self.planstats.digest_count(),
            "totalRecorded": self.planstats.total_recorded,
            "topByCount": self.planstats.top(top, by="count", tables=tables),
            "topByCost": self.planstats.top(top, by="cost", tables=tables),
        }

    # ------------------------------------------------------------------
    # resilient scatter-gather
    # ------------------------------------------------------------------
    def _hedge_delay_s(self) -> Optional[float]:
        """Hedge trigger delay: the observed server-latency percentile
        once enough samples exist, else the configured static floor.
        ``hedge_delay_ms <= 0`` disables hedging entirely."""
        if self.hedge_delay_ms <= 0:
            return None
        timer = self.metrics.timer("serverLatency")
        if timer.count >= 20:
            return max(timer.percentile(self.hedge_latency_percentile), 1.0) / 1000.0
        return self.hedge_delay_ms / 1000.0

    def _backoff_s(self, reissues: int) -> float:
        return (
            min(self.retry_backoff_ms * (2 ** max(0, reissues - 1)), self.retry_backoff_cap_ms)
            / 1000.0
        )

    def _scatter_gather(
        self,
        request: BrokerRequest,
        batches: List[_Batch],
        timeout_ms: float,
        logical_table: str,
        request_id: str,
        ctx: TraceContext,
        extra_fn=None,
    ) -> Tuple[List[IntermediateResult], Dict[str, Any]]:
        # request_id is REQUIRED: minting a fallback here would hand the
        # servers a different id than the one echoed to the client,
        # silently breaking the correlation contract
        deadline = time.monotonic() + timeout_ms / 1000.0
        # (batch.order, result): parts merge in BATCH CREATION order, not
        # completion order — ties in sort keys (and any other
        # order-sensitive reduce step) must not depend on which server
        # replied first
        ordered_parts: List[Tuple[int, IntermediateResult]] = []
        exceptions: List[QueryException] = []
        unserved: List[str] = []
        servers_queried: Set[str] = set()
        servers_responded: Set[str] = set()
        retries = 0
        hedges = 0
        hedge_delay_s = self._hedge_delay_s()
        if hedge_delay_s is not None and (
            self.quota.headroom(logical_table) < self.hedge_min_quota_headroom
        ):
            # hedging doubles this table's scatter traffic; near the QPS
            # quota that amplification would starve first-try queries
            hedge_delay_s = None

        # future -> (batch, server, is_hedge, sent_at, wall_sent_ms)
        pending: Dict[concurrent.futures.Future, Tuple[_Batch, str, bool, float, float]] = {}
        all_batches: List[_Batch] = list(batches)
        delayed: List[Tuple[float, _Batch]] = []  # (fire_time, batch) backoff queue
        open_lineages = len(batches)  # batches neither completed nor superseded
        # (attempt span id, {scope: spans}) per merged server reply —
        # handle_pql re-parents each tree under its attempt span
        server_traces: List[Tuple[Optional[str], Dict[str, Any]]] = []

        def attempt_span(
            batch: _Batch, server: str, hedge: bool, sent_at: float,
            wall_sent: float, status: str, **tags
        ) -> Optional[str]:
            return ctx.add(
                "serverAttempt",
                (time.monotonic() - sent_at) * 1000.0,
                start_ms=wall_sent,
                server=server,
                hedge=hedge,
                reissues=batch.reissues,
                segments=len(batch.segments),
                status=status,
                **tags,
            )

        def submit(batch: _Batch, server: str, hedge: bool = False) -> None:
            now = time.monotonic()
            remaining_ms = max(1.0, (deadline - now) * 1000.0)
            servers_queried.add(server)
            # half-open probe claim: a penalty-boxed server chosen after
            # its window gets exactly ONE probe marked inflight, so
            # concurrent queries keep steering around it until the probe
            # reports back (no thundering herd onto a sick server)
            self.health.allow_request(server)
            # with retries in reserve AND an untried replica to fail over
            # to, wait only half the remaining budget on this attempt: a
            # hung (not refusing) replica then surfaces as a transport
            # timeout while there is still time to re-issue elsewhere.
            # With no alternate (or on the last attempt) waiting less
            # than the full budget could only turn a slow success into a
            # guaranteed miss.
            retries_left = self.retry_attempts - batch.reissues
            attempt_ms = remaining_ms
            if retries_left > 0 and not hedge and self.routing.has_alternate(
                batch.table, batch.segments, batch.excluded
            ):
                attempt_ms = remaining_ms / 2.0
            fut = self._pool.submit(
                self._send_one,
                server,
                batch.table,
                batch.pql,
                batch.segments,
                request.enable_trace,
                request.debug_options or None,
                remaining_ms,
                attempt_ms,
                request_id,
                extra_fn(server) if extra_fn is not None else None,
            )
            # AIMD window accounting: the done-callback observes EVERY
            # attempt outcome exactly once — including attempts that
            # outlive this query's gather loop (deadline-abandoned
            # transports complete later and still decrement in-flight)
            self.admission.on_attempt_start(server)
            fut.add_done_callback(
                lambda f, s=server: self._observe_attempt(f, s)
            )
            batch.inflight += 1
            if not hedge:
                batch.first_sent = now
            pending[fut] = (batch, server, hedge, now, time.time() * 1000.0)

        def fail_batch(batch: _Batch) -> None:
            nonlocal open_lineages
            unserved.extend(batch.segments)
            exceptions.extend(batch.errors)
            batch.done = True
            open_lineages -= 1

        def failover(batch: _Batch) -> None:
            """All inflight attempts for this lineage failed: re-cover
            its segments on untried replicas, or declare them unserved."""
            nonlocal retries, open_lineages
            if batch.reissues >= self.retry_attempts:
                fail_batch(batch)
                return
            assignment, leftover = self.routing.alternates(
                batch.table, batch.segments, batch.excluded, health=self.health
            )
            child_errors = batch.errors
            if leftover:
                exceptions.extend(batch.errors)
                unserved.extend(leftover)
                # already reported above: children start clean so a later
                # child failure doesn't duplicate the ancestry in the
                # response's exceptions
                child_errors = []
            if not assignment:
                if not leftover:  # alternates() returned nothing at all
                    fail_batch(batch)
                else:
                    batch.done = True
                    open_lineages -= 1
                return
            batch.done = True  # superseded by its children
            open_lineages -= 1
            for server, segments in assignment.items():
                child = _Batch(
                    batch.table,
                    batch.pql,
                    segments,
                    server,
                    excluded=batch.excluded,
                    reissues=batch.reissues + 1,
                    errors=child_errors,
                    order=batch.order,  # failover keeps the merge slot
                )
                all_batches.append(child)
                open_lineages += 1
                retries += 1
                self.metrics.meter("failoverRetries").mark()
                ctx.event(
                    "failover",
                    fromServer=batch.server,
                    toServer=server,
                    segments=len(segments),
                    reissues=child.reissues,
                )
                fire = time.monotonic() + self._backoff_s(child.reissues)
                if fire >= deadline:
                    # no budget left to back off AND run the query; try
                    # immediately rather than guaranteeing a miss
                    submit(child, server)
                else:
                    delayed.append((fire, child))

        for batch in batches:
            submit(batch, batch.server)

        while open_lineages > 0 and (pending or delayed):
            now = time.monotonic()
            if now >= deadline:
                break
            # fire due backoff retries
            due = [(f, b) for f, b in delayed if f <= now]
            if due:
                delayed = [(f, b) for f, b in delayed if f > now]
                for _, batch in due:
                    submit(batch, batch.server)
            # arm hedges on stragglers
            next_hedge = math.inf
            if hedge_delay_s is not None:
                for batch, server, hedge, _sent, _wall in list(pending.values()):
                    if hedge or batch.done or batch.hedged:
                        continue
                    fire = batch.first_sent + hedge_delay_s
                    if fire > now:
                        next_hedge = min(next_hedge, fire)
                        continue
                    assignment, leftover = self.routing.alternates(
                        batch.table, batch.segments, batch.excluded, health=self.health
                    )
                    batch.hedged = True  # one hedge round per lineage
                    # a hedge reply REPLACES the primary's, so it must
                    # cover the identical segment set: a replica holding
                    # only part of it would win the race with silently
                    # missing data.  Split coverage -> no hedge (failover
                    # still handles an eventual primary failure).
                    if len(assignment) == 1 and not leftover:
                        alt_server = next(iter(assignment))
                        batch.excluded.add(alt_server)
                        hedges += 1
                        self.metrics.meter("hedgesSent").mark()
                        ctx.event(
                            "hedge", fromServer=server, toServer=alt_server,
                            segments=len(batch.segments),
                        )
                        submit(batch, alt_server, hedge=True)
            if not pending:
                # nothing inflight: sleep until the next backoff fire
                next_fire = min((f for f, _ in delayed), default=deadline)
                time.sleep(max(0.0, min(next_fire, deadline) - time.monotonic()))
                continue
            next_event = min(deadline, next_hedge, *(f for f, _ in delayed)) \
                if delayed else min(deadline, next_hedge)
            done, _ = concurrent.futures.wait(
                list(pending.keys()),
                timeout=max(0.0, next_event - time.monotonic()),
                return_when=concurrent.futures.FIRST_COMPLETED,
            )
            for fut in done:
                batch, server, hedge, sent_at, wall_sent = pending.pop(fut)
                batch.inflight -= 1
                try:
                    result = fut.result()
                except concurrent.futures.CancelledError:
                    # a queued twin we cancelled after its batch already
                    # completed — not a server failure, not data
                    continue
                except Exception as e:
                    self.health.record_failure(server)
                    logger.warning("server %s failed: %s", server, e)
                    attempt_span(
                        batch, server, hedge, sent_at, wall_sent,
                        "error", error=f"{type(e).__name__}: {e}"[:200],
                    )
                    batch.errors.append(
                        QueryException(
                            ErrorCode.BROKER_GATHER,
                            f"server {server}: {type(e).__name__}: {e}",
                        )
                    )
                    if not batch.done and batch.inflight == 0:
                        failover(batch)
                    continue
                retryable = result.exceptions and all(
                    code in RETRYABLE_SERVER_CODES for code, _ in result.exceptions
                )
                if retryable:
                    # the server answered "not me, not now" (saturated /
                    # draining): treat as failover-able, not as data
                    self.health.record_failure(server)
                    attempt_span(
                        batch, server, hedge, sent_at, wall_sent,
                        "refused", errorCode=result.exceptions[0][0],
                    )
                    batch.errors.append(
                        QueryException(result.exceptions[0][0], result.exceptions[0][1])
                    )
                    if not batch.done and batch.inflight == 0:
                        failover(batch)
                    continue
                self.health.record_success(server)
                # per-ATTEMPT latency (a winning hedge measures from its
                # own send, not the primary's — else the percentile that
                # arms future hedges inflates itself)
                self.metrics.timer("serverLatency").update(
                    (time.monotonic() - sent_at) * 1000.0
                )
                if batch.done:
                    # hedge race loser: first reply already merged; the
                    # attempt still shows on the waterfall as the slower
                    # twin, but its data (and trace) is discarded
                    attempt_span(
                        batch, server, hedge, sent_at, wall_sent, "hedgeLoser"
                    )
                    continue
                aid = attempt_span(batch, server, hedge, sent_at, wall_sent, "ok")
                if result.trace:
                    # snapshot: reduce later merges parts IN PLACE, which
                    # would fold every later part's spans into the first
                    # reply's trace dict (aliased here)
                    server_traces.append(
                        (aid, {k: list(v) for k, v in result.trace.items()})
                    )
                batch.done = True
                open_lineages -= 1
                servers_responded.add(server)
                ordered_parts.append((batch.order, result))
                # server-reported unserved segments (dropped on that
                # server / quarantined pending re-fetch): the served
                # part merges above; the missing slice re-covers on an
                # untried replica or degrades honestly
                batch_set = set(batch.segments)
                missing = [s for s in result.unserved_segments if s in batch_set]
                if missing:
                    merr = QueryException(
                        ErrorCode.SERVER_SEGMENT_MISSING,
                        f"server {server}: segments unavailable: {sorted(missing)}",
                    )
                    assignment: Dict[str, List[str]] = {}
                    leftover = list(missing)
                    if batch.reissues < self.retry_attempts:
                        assignment, leftover = self.routing.alternates(
                            batch.table, missing, batch.excluded, health=self.health
                        )
                    if leftover:
                        exceptions.append(merr)
                        unserved.extend(leftover)
                    for alt_server, alt_segments in assignment.items():
                        child = _Batch(
                            batch.table,
                            batch.pql,
                            alt_segments,
                            alt_server,
                            excluded=batch.excluded,
                            reissues=batch.reissues + 1,
                            errors=[] if leftover else [merr],
                            order=batch.order,
                        )
                        all_batches.append(child)
                        open_lineages += 1
                        retries += 1
                        self.metrics.meter("failoverRetries").mark()
                        submit(child, alt_server)
                # best effort: free the loser's queued twin if it never started
                for other, (ob, _osrv, _oh, _osent, _owall) in list(pending.items()):
                    if ob is batch:
                        other.cancel()

        # deadline expired (or queue drained): account every lineage that
        # never completed
        for fut, (pbatch, pserver, _h, _sent, _wall) in pending.items():
            if not pbatch.done and not fut.cancel():
                attempt_span(pbatch, pserver, _h, _sent, _wall, "timeout")
                # an attempt for a still-open lineage ran past the
                # deadline: the circuit breaker must learn about hung
                # servers too, or a blackholed replica would stay CLOSED
                # (and keep being routed to) forever — no exception ever
                # surfaces to the gather loop once the query returns.
                # (Hedge losers of COMPLETED batches are just slower,
                # not sick — they are skipped.)
                self.health.record_failure(pserver)
        for batch in all_batches:
            if not batch.done and batch.inflight > 0:
                batch.errors.append(
                    QueryException(
                        ErrorCode.BROKER_TIMEOUT,
                        f"server {batch.server}: no reply within {timeout_ms:.0f}ms budget",
                    )
                )
                fail_batch(batch)
            elif not batch.done:
                fail_batch(batch)

        ordered_parts.sort(key=lambda pair: pair[0])  # stable: children keep arrival order
        parts = [result for _, result in ordered_parts]
        return parts, {
            "exceptions": exceptions,
            "unserved": unserved,
            "servers_queried": servers_queried,
            "servers_responded": servers_responded,
            "retries": retries,
            "hedges": hedges,
            "server_traces": server_traces,
        }

    def _observe_attempt(self, fut: concurrent.futures.Future, server: str) -> None:
        """Feed one finished scatter attempt into the AIMD admission
        windows: transport failures and retryable (210/220) refusals are
        saturation evidence (multiplicative decrease); a healthy reply
        grows the window additively unless its backpressure snapshot
        shows the server's scheduler past the high-water mark."""
        if fut.cancelled():
            self.admission.on_attempt_cancelled(server)
            return
        exc = fut.exception()
        if exc is not None:
            self.admission.on_attempt_done(server, saturated=True)
            return
        result = fut.result()
        refused = bool(result.exceptions) and all(
            code in RETRYABLE_SERVER_CODES for code, _ in result.exceptions
        )
        self.admission.on_attempt_done(
            server, saturated=refused, backpressure=result.backpressure
        )

    # ------------------------------------------------------------------
    def _physical_tables(self, table: str, pql: str) -> List[Tuple[str, str]]:
        """Logical table -> [(physical table, sub-query pql)].

        Hybrid federation (BrokerRequestHandler.java:280-329): a table
        with both OFFLINE and REALTIME physical tables gets the query
        duplicated with a time-boundary filter added on each side.
        """
        known = set(self.routing.tables())
        if table in known:
            return [(table, pql)]
        offline = table + OFFLINE_SUFFIX
        realtime = table + REALTIME_SUFFIX
        if offline in known and realtime in known:
            boundary = self.time_boundary.get(offline)
            if boundary is not None:
                col, value = boundary
                return [
                    (offline, self._with_time_filter(pql, col, value, is_offline=True)),
                    (realtime, self._with_time_filter(pql, col, value, is_offline=False)),
                ]
            return [(offline, pql)]
        if offline in known:
            return [(offline, pql)]
        if realtime in known:
            return [(realtime, pql)]
        return []

    def _with_time_filter(self, pql: str, col: str, value: int, is_offline: bool) -> str:
        """Append the hybrid time-boundary predicate to the PQL text
        (offline: col <= boundary; realtime: col > boundary —
        HelixExternalViewBasedTimeBoundaryService.java:52-85)."""
        op = "<=" if is_offline else ">"
        upper = pql.upper()
        pred = f"{col} {op} {value}"
        if " WHERE " in upper:
            idx = upper.index(" WHERE ") + len(" WHERE ")
            rest = pql[idx:]
            # predicate list ends at the next clause keyword
            end = len(rest)
            for kw in (" GROUP BY ", " ORDER BY ", " HAVING ", " TOP ", " LIMIT "):
                j = rest.upper().find(kw)
                if j != -1:
                    end = min(end, j)
            return pql[:idx] + f"({rest[:end]}) AND {pred}" + rest[end:]
        # insert WHERE after FROM <table>
        ufrom = upper.index(" FROM ")
        after = pql[ufrom + len(" FROM ") :]
        stop = len(after)
        for kw in (" WHERE ", " GROUP BY ", " ORDER BY ", " HAVING ", " TOP ", " LIMIT "):
            j = after.upper().find(kw)
            if j != -1:
                stop = min(stop, j)
        return (
            pql[: ufrom + len(" FROM ")] + after[:stop] + f" WHERE {pred}" + after[stop:]
        )

    def _send_one(
        self,
        server: str,
        table: str,
        pql: str,
        segments: List[str],
        trace: bool,
        debug_options: Optional[Dict[str, str]],
        timeout_ms: float,
        attempt_timeout_ms: Optional[float],
        request_id: str,
        join: Optional[Dict[str, Any]] = None,
    ) -> IntermediateResult:
        # timeout_ms is the REMAINING deadline budget at (re-)issue time,
        # already clamped by handle_request — the server's scheduler pins
        # it as its dequeue deadline (deadline propagation).
        # attempt_timeout_ms caps how long the BROKER waits on this one
        # attempt: when retries remain, it is a fraction of the budget so
        # a hung replica surfaces as a transport timeout early enough to
        # fail over (the server keeps the full budget — wasted work at
        # worst, not an early server-side timeout).
        address = self.server_addresses[server]
        payload = serialize_instance_request(
            request_id,
            pql,
            table,
            segments,
            timeout_ms,
            trace=trace,
            debug_options=debug_options,
            join=join,
        )
        wait_ms = timeout_ms if attempt_timeout_ms is None else attempt_timeout_ms
        reply = self.transport.request(address, payload, timeout=wait_ms / 1000.0)
        return deserialize_result(reply)


# ---------------------------------------------------------------------------
# HTTP front (PinotClientRequestServlet analog)
# ---------------------------------------------------------------------------


class InvalidTimeoutError(ValueError):
    """A timeoutMs override was present but not a positive number."""


def _parse_timeout(v) -> Optional[float]:
    """Strict per-query timeoutMs: absent (None/empty) means "use the
    broker default"; anything present must be a positive finite number
    or the query is rejected with a validation error — a silently
    ignored override is worse than a loud one (the client believes a
    budget it never got)."""
    if v is None or v == "":
        return None
    if isinstance(v, bool):  # float(True) == 1.0 — a flag is junk here
        raise InvalidTimeoutError(f"timeoutMs must be a positive number, got {v!r}")
    try:
        t = float(v)
    except (TypeError, ValueError):
        raise InvalidTimeoutError(f"timeoutMs must be a positive number, got {v!r}")
    if math.isnan(t) or math.isinf(t) or t <= 0:
        raise InvalidTimeoutError(f"timeoutMs must be a positive number, got {v!r}")
    return t


def _parse_debug_options(s: str) -> Optional[Dict[str, str]]:
    """``"k=v;k2=v2"`` -> dict (the reference's semicolon/equals debug
    option string, ``BrokerRequestHandler.java:156-159``)."""
    if not s:
        return None
    out: Dict[str, str] = {}
    for part in s.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out or None


class BrokerHttpServer:
    """HTTP endpoint: GET /query?pql=... and POST /query {"pql": ...}
    (``PinotClientRequestServlet.java:54/:73``)."""

    def __init__(self, handler: BrokerRequestHandler, host: str = "127.0.0.1", port: int = 0):
        broker = handler

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _respond(self, payload: Dict[str, Any], status: int = 200) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _respond_text(self, text: str, status: int = 200) -> None:
                body = text.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _invalid_timeout(self, e: InvalidTimeoutError) -> None:
                self._respond(
                    BrokerResponse(
                        exceptions=[QueryException(ErrorCode.QUERY_VALIDATION, str(e))]
                    ).to_json()
                )

            def do_GET(self):
                url = urlparse(self.path)
                if url.path not in ("/query", "/"):
                    if url.path == "/health":
                        return self._respond({"status": "ok"})
                    if url.path == "/metrics":
                        # Prometheus text exposition (scrape target)
                        return self._respond_text(prometheus_text(broker.metrics))
                    if url.path == "/debug/metrics":
                        return self._respond(broker.metrics.snapshot())
                    if url.path == "/debug/queries":
                        return self._respond(broker.querylog.snapshot())
                    if url.path == "/debug/admission":
                        return self._respond(broker.admission.snapshot())
                    if url.path == "/debug/history":
                        return self._respond(
                            broker.history.query_from_qs(url.query)
                        )
                    if url.path == "/debug/slo":
                        return self._respond(broker.slo.snapshot())
                    if url.path == "/debug/tails":
                        qs = parse_qs(url.query)
                        rid = (qs.get("requestId") or [""])[0]
                        if rid:
                            entry = broker.tail.get(rid)
                            if entry is None:
                                return self._respond(
                                    {"error": f"no retained tail for {rid}"},
                                    404,
                                )
                            return self._respond(entry)
                        try:
                            top = int((qs.get("top") or ["20"])[0])
                        except ValueError:
                            top = 20
                        traces = (
                            (qs.get("traces") or ["false"])[0].lower() == "true"
                        )
                        return self._respond(
                            broker.tail.snapshot(
                                top=max(1, top), include_traces=traces
                            )
                        )
                    if url.path == "/debug/flightrec":
                        return self._respond(broker.flightrec.snapshot())
                    if url.path == "/debug/audit":
                        # correctness & freshness plane: replica-audit
                        # counters + the event-time watermark summary
                        from pinot_tpu.broker.freshness import WATERMARKS

                        return self._respond(
                            {
                                "replica": broker.replica_audit.snapshot(),
                                "freshness": WATERMARKS.snapshot(),
                            }
                        )
                    if url.path == "/debug/workload":
                        qs = parse_qs(url.query)
                        # ?n= is the prewarm-facing alias for ?top=
                        raw_top = (qs.get("n") or qs.get("top") or ["20"])[0]
                        try:
                            top = int(raw_top)
                        except ValueError:
                            top = 20
                        raw_tables = (qs.get("tables") or [""])[0]
                        tables = [
                            t.strip()
                            for t in raw_tables.split(",")
                            if t.strip()
                        ] or None
                        return self._respond(
                            broker.workload_snapshot(
                                top=max(1, top), tables=tables
                            )
                        )
                    if url.path == "/serverhealth":
                        return self._respond(
                            {
                                "circuits": broker.health.snapshot(),
                                "drainingServers": sorted(broker.draining_servers),
                                "warmingServers": sorted(
                                    broker.health.warming_servers()
                                ),
                            }
                        )
                    return self._respond({"error": "not found"}, 404)
                qs = parse_qs(url.query)
                pql = (qs.get("pql") or qs.get("bql") or [""])[0]
                trace = (qs.get("trace") or ["false"])[0].lower() == "true"
                debug = _parse_debug_options((qs.get("debugOptions") or [""])[0])
                try:
                    timeout_ms = _parse_timeout((qs.get("timeoutMs") or [""])[0])
                except InvalidTimeoutError as e:
                    return self._invalid_timeout(e)
                resp = broker.handle_pql(
                    pql,
                    trace=trace,
                    debug_options=debug,
                    timeout_ms=timeout_ms,
                )
                self._respond(resp.to_json())

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError as e:
                    return self._respond(
                        {"exceptions": [{"errorCode": ErrorCode.JSON_PARSING, "message": str(e)}]}
                    )
                pql = body.get("pql") or body.get("bql") or ""
                debug = body.get("debugOptions") or ""
                if isinstance(debug, dict):
                    debug = {str(k): str(v) for k, v in debug.items()}
                else:
                    # the reference's "k=v;k2=v2" string form; any other
                    # JSON type is ignored rather than crashing the handler
                    debug = _parse_debug_options(debug if isinstance(debug, str) else "")
                try:
                    timeout_ms = _parse_timeout(body.get("timeoutMs"))
                except InvalidTimeoutError as e:
                    return self._invalid_timeout(e)
                resp = broker.handle_pql(
                    pql,
                    trace=bool(body.get("trace")),
                    debug_options=debug,
                    timeout_ms=timeout_ms,
                )
                self._respond(resp.to_json())

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
