"""Networked broker starter: a broker process joining a remote controller.

The in-process ``BrokerStarter`` gets external-view callbacks directly;
this variant polls the controller's versioned cluster-state snapshot
(the ZK-watch analog of ``HelixBrokerStarter.java:57`` +
``ClusterChangeMediator``) and rebuilds:

- per-table routing tables (one random ONLINE replica per segment),
- the server-name -> TCP-address map used by scatter-gather,
- hybrid time boundaries and per-table query quotas.

Queries ride the same path as in-process deployments: HTTP front ->
``BrokerRequestHandler`` -> TCP scatter-gather -> reduce.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Any, Dict, Optional

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.transport.tcp import TcpTransport

logger = logging.getLogger(__name__)


class NetworkedBrokerStarter:
    def __init__(
        self,
        controller_url: str,
        name: str = "broker0",
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval_s: float = 1.0,
        poll_interval_s: float = 0.3,
        conf=None,
    ) -> None:
        self.controller_url = controller_url.rstrip("/")
        self.name = name
        if conf is not None:
            # BrokerConf resilience knobs (retry/hedge/circuit-breaker)
            self.handler = BrokerRequestHandler.from_conf(
                TcpTransport(), {}, conf, name=name
            )
        else:
            self.handler = BrokerRequestHandler(TcpTransport(), {}, name=name)
        self.http = BrokerHttpServer(self.handler, host=host, port=port)
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self._version = -1
        self._epoch = ""  # controller incarnation (see /clusterstate)
        self._dead_servers: set = set()
        self._stop = threading.Event()
        self._threads: list = []

    def _post(self, path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.controller_url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def _get(self, path: str) -> Dict[str, Any]:
        with urllib.request.urlopen(self.controller_url + path, timeout=10) as r:
            return json.loads(r.read())

    def start(self) -> None:
        self.http.start()
        self._register()
        self._refresh(force=True)
        for fn in (self._heartbeat_loop, self._poll_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self.http.stop()

    def _register(self) -> None:
        self._post(
            "/instances",
            {
                "name": self.name,
                "role": "broker",
                "url": f"http://{self.http.host}:{self.http.port}",
            },
        )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                out = self._post(f"/instances/{self.name}/heartbeat", {})
                if out.get("reregister"):
                    self._register()
            except Exception as e:
                logger.warning("heartbeat to controller failed: %s", e)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._refresh()
            except Exception as e:
                logger.warning("cluster-state poll failed: %s", e)

    def _refresh(self, force: bool = False) -> None:
        state = self._get(
            f"/clusterstate?ifNewer={-1 if force else self._version}"
            f"&epoch={self._epoch}"
        )
        if state.get("unchanged"):
            return
        self._apply_state(state)

    def _apply_state(self, state: Dict[str, Any]) -> None:
        """Apply one versioned cluster-state snapshot (split out of
        ``_refresh`` so the quota/routing propagation rules are testable
        against synthetic snapshots)."""
        self._version = state["version"]
        self._epoch = state.get("epoch", "")
        for server, addr in state["servers"].items():
            self.handler.set_server_address(server, (addr[0], int(addr[1])))
        # controller-declared liveness TRANSITIONS feed the circuit
        # breaker on the same versioned snapshot that rebuilds routing;
        # steady-state polls must not touch data-plane-opened circuits
        dead = set(state.get("deadServers", []))
        for server in dead - self._dead_servers:
            self.handler.health.mark_dead(server)
        for server in self._dead_servers - dead:
            self.handler.health.mark_alive(server)
        self._dead_servers = dead
        # draining servers already dropped out of the snapshot's routing
        # views (so no new covers land on them) but stay healthy and
        # addressable for in-flight work — surfaced at /serverhealth so
        # ops can tell a deliberate drain from a sick circuit
        self.handler.draining_servers = set(state.get("drainingServers", []))
        known = set(self.handler.routing.tables())
        for table, view in state["tables"].items():
            self.handler.routing.update(table, view)
            known.discard(table)
        for stale in known:
            self.handler.routing.remove(stale)
            self.handler.time_boundary.remove(stale)
        for table, (col, value) in state.get("timeBoundaries", {}).items():
            self.handler.time_boundary.set(table, col, value)
        # quota propagation contract: an UPDATE reaches this broker on
        # the next poll (set_quota reconfigures the live bucket in place
        # — tokens preserved, so a poll can never act as a refill), and
        # a REMOVAL clears the bucket (tables whose quota left the
        # snapshot must stop being rate-limited)
        quota_raw_names = set()
        for table, q in state.get("quotas", {}).items():
            raw = q["rawName"]
            quota_raw_names.add(raw)
            self.handler.quota.set_quota(
                raw, q.get("maxQueriesPerSecond"), q.get("burstQueries")
            )
        for stale in set(self.handler.quota.tables()) - quota_raw_names:
            self.handler.quota.set_quota(stale, None)
