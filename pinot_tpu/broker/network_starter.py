"""Networked broker starter: a broker process joining a remote controller.

The in-process ``BrokerStarter`` gets external-view callbacks directly;
this variant polls the controller's versioned cluster-state snapshot
(the ZK-watch analog of ``HelixBrokerStarter.java:57`` +
``ClusterChangeMediator``) and rebuilds:

- per-table routing tables (one random ONLINE replica per segment),
- the server-name -> TCP-address map used by scatter-gather,
- hybrid time boundaries and per-table query quotas.

Queries ride the same path as in-process deployments: HTTP front ->
``BrokerRequestHandler`` -> TCP scatter-gather -> reduce.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Any, Dict, Optional

from pinot_tpu.broker.broker import BrokerHttpServer, BrokerRequestHandler
from pinot_tpu.transport.tcp import TcpTransport

logger = logging.getLogger(__name__)


class NetworkedBrokerStarter:
    def __init__(
        self,
        controller_url: str,
        name: str = "broker0",
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_interval_s: float = 1.0,
        poll_interval_s: float = 0.3,
        conf=None,
        fault_injector=None,
    ) -> None:
        self.controller_url = controller_url.rstrip("/")
        self.name = name
        # link-level chaos hook (common/faults.py): the clusterstate
        # poll/heartbeat ride link (name -> "controller"), and the
        # scatter transport consults the injector per server link
        self.fault_injector = fault_injector
        transport = TcpTransport()
        if fault_injector is not None:
            from pinot_tpu.common.faults import LinkFaultTransport

            transport = LinkFaultTransport(
                transport, fault_injector, src=name,
                resolve=self._server_of_address,
            )
        if conf is not None:
            # BrokerConf resilience knobs (retry/hedge/circuit-breaker)
            self.handler = BrokerRequestHandler.from_conf(
                transport, {}, conf, name=name
            )
        else:
            self.handler = BrokerRequestHandler(transport, {}, name=name)
        if fault_injector is not None:
            # netfaults.* attribution on THIS broker's registry (the
            # handler — and so the registry — exists only now)
            transport.metrics = self.handler.metrics
        self.http = BrokerHttpServer(self.handler, host=host, port=port)
        self.heartbeat_interval_s = heartbeat_interval_s
        self.poll_interval_s = poll_interval_s
        self._version = -1
        self._epoch = ""  # controller incarnation (see /clusterstate)
        self._dead_servers: set = set()
        self._stop = threading.Event()
        self._threads: list = []
        # partition observability + jittered retry cadence: while the
        # controller is unreachable this broker keeps serving from its
        # last versioned snapshot and says so on a gauge
        from pinot_tpu.utils.retry import FullJitterBackoff

        self._poll_backoff = FullJitterBackoff(
            initial_s=max(0.1, poll_interval_s), cap_s=10.0
        )
        # heartbeat backoff stays under typical liveness timeouts: under
        # an asymmetric partition (replies lost, requests arriving) a
        # deep backoff would flap this live broker dead at the controller
        self._hb_backoff = FullJitterBackoff(
            initial_s=max(0.1, heartbeat_interval_s), cap_s=2.0
        )
        # per-request timeout for heartbeats, tightened with the backoff
        # cap (_register) so a blackholed request fails well before the
        # liveness window elapses
        self._hb_timeout_s = 10.0
        self.handler.metrics.gauge("controller.unreachable").set(0)
        self.handler.metrics.meter("controller.pollFailures")
        self.handler.metrics.meter("controller.allDeadSnapshotsHeld")

    def _server_of_address(self, address) -> str:
        """Reverse-resolve a TCP address to the server's instance name
        for link-injection (falls back to ``host:port``)."""
        addr = (address[0], int(address[1]))
        # snapshot: the poll thread mutates this dict via
        # set_server_address while scatter calls resolve concurrently
        for server, known in list(self.handler.server_addresses.items()):
            if (known[0], int(known[1])) == addr:
                return server
        return f"{address[0]}:{address[1]}"

    def _link(self, fn):
        from pinot_tpu.common.faults import call_on_controller_link

        return call_on_controller_link(
            self.fault_injector, self.name, fn, metrics=self.handler.metrics
        )

    def _post(
        self, path: str, payload: Dict[str, Any], timeout_s: float = 10.0
    ) -> Dict[str, Any]:
        def send():
            req = urllib.request.Request(
                self.controller_url + path,
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read())

        return self._link(send)

    def _get(self, path: str) -> Dict[str, Any]:
        def send():
            with urllib.request.urlopen(self.controller_url + path, timeout=10) as r:
                return json.loads(r.read())

        return self._link(send)

    def start(self) -> None:
        self.http.start()
        self._register()
        self._refresh(force=True)
        for fn in (self._heartbeat_loop, self._poll_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self.http.stop()
        self.handler.shutdown()

    def _register(self) -> None:
        # rides the heartbeat loop on reregister: must respect the same
        # tightened request timeout as the heartbeats themselves (a 10s
        # blackholed POST would blow the liveness window on its own)
        out = self._post(
            "/instances",
            {
                "name": self.name,
                "role": "broker",
                "url": f"http://{self.http.host}:{self.http.port}",
            },
            timeout_s=self._hb_timeout_s,
        )
        # keep the worst-case heartbeat gap under the controller's
        # advertised liveness timeout (same reasoning as the server
        # starter: an asymmetric partition must not flap us dead) —
        # backoff cap and per-request timeout each take a third of the
        # window
        timeout = out.get("heartbeatTimeoutSeconds")
        if timeout:
            from pinot_tpu.utils.retry import tighten_liveness_budget

            self._hb_timeout_s = tighten_liveness_budget(
                self._hb_backoff, float(timeout), self._hb_timeout_s
            )

    def _heartbeat_loop(self) -> None:
        wait_s = self.heartbeat_interval_s
        while not self._stop.wait(wait_s):
            try:
                out = self._post(
                    f"/instances/{self.name}/heartbeat",
                    {},
                    timeout_s=self._hb_timeout_s,
                )
                if out.get("reregister"):
                    self._register()
                self._hb_backoff.reset()
                wait_s = self.heartbeat_interval_s
            except Exception as e:
                wait_s = self._hb_backoff.next_delay()
                logger.warning(
                    "heartbeat to controller failed (retry in %.2fs): %s",
                    wait_s, e,
                )

    def _poll_loop(self) -> None:
        wait_s = self.poll_interval_s
        unreachable = self.handler.metrics.gauge("controller.unreachable")
        while not self._stop.wait(wait_s):
            try:
                self._refresh()
                self._poll_backoff.reset()
                unreachable.set(0)
                wait_s = self.poll_interval_s
            except Exception as e:
                # partitioned from the controller: this broker keeps
                # routing from its last VERSIONED snapshot (already
                # applied atomically) and retries with full jitter —
                # visible on the controller.unreachable gauge
                self.handler.metrics.meter("controller.pollFailures").mark()
                unreachable.set(1)
                wait_s = self._poll_backoff.next_delay()
                logger.warning(
                    "cluster-state poll failed (retry in %.2fs): %s", wait_s, e
                )

    def _refresh(self, force: bool = False) -> None:
        state = self._get(
            f"/clusterstate?ifNewer={-1 if force else self._version}"
            f"&epoch={self._epoch}"
        )
        if state.get("unchanged"):
            return
        self._apply_state(state)

    def _apply_state(self, state: Dict[str, Any]) -> None:
        """Apply one versioned cluster-state snapshot (split out of
        ``_refresh`` so the quota/routing propagation rules are testable
        against synthetic snapshots)."""
        if not state.get("servers") and self.handler.server_addresses:
            # the controller says EVERY server is gone while we hold
            # live routing.  That is epistemically indistinguishable
            # from the CONTROLLER having been the partitioned one
            # (e.g. the whole fleet's heartbeats are still in their
            # post-heal backoff): keep serving from the last snapshot —
            # if the fleet is truly down the scatter fails identically,
            # and if the controller is wrong we stay available.  The
            # version is NOT advanced, so every poll refetches until
            # the controller sees servers again.
            self.handler.metrics.meter("controller.allDeadSnapshotsHeld").mark()
            logger.warning(
                "cluster-state snapshot lists no live servers; holding "
                "the previous routing (version %d)", self._version,
            )
            return
        self._version = state["version"]
        self._epoch = state.get("epoch", "")
        for server, addr in state["servers"].items():
            self.handler.set_server_address(server, (addr[0], int(addr[1])))
        # controller-declared liveness TRANSITIONS feed the circuit
        # breaker on the same versioned snapshot that rebuilds routing;
        # steady-state polls must not touch data-plane-opened circuits
        dead = set(state.get("deadServers", []))
        for server in dead - self._dead_servers:
            self.handler.health.mark_dead(server)
        for server in self._dead_servers - dead:
            self.handler.health.mark_alive(server)
        self._dead_servers = dead
        # draining servers already dropped out of the snapshot's routing
        # views (so no new covers land on them) but stay healthy and
        # addressable for in-flight work — surfaced at /serverhealth so
        # ops can tell a deliberate drain from a sick circuit
        self.handler.draining_servers = set(state.get("drainingServers", []))
        # warming servers stay fully routable; routing just prefers a
        # ready replica while the restarted server rebuilds its compile
        # working set (heartbeat-reported readiness, see server starter)
        self.handler.health.set_warming_servers(state.get("warmingServers", []))
        known = set(self.handler.routing.tables())
        for table, view in state["tables"].items():
            self.handler.routing.update(table, view)
            known.discard(table)
        for stale in known:
            self.handler.routing.remove(stale)
            self.handler.time_boundary.remove(stale)
        for table, (col, value) in state.get("timeBoundaries", {}).items():
            self.handler.time_boundary.set(table, col, value)
        # quota propagation contract: an UPDATE reaches this broker on
        # the next poll (set_quota reconfigures the live bucket in place
        # — tokens preserved, so a poll can never act as a refill), and
        # a REMOVAL clears the bucket (tables whose quota left the
        # snapshot must stop being rate-limited)
        quota_raw_names = set()
        for table, q in state.get("quotas", {}).items():
            raw = q["rawName"]
            quota_raw_names.add(raw)
            self.handler.quota.set_quota(
                raw, q.get("maxQueriesPerSecond"), q.get("burstQueries")
            )
            # per-table SLO objectives ride the same snapshot; an absent
            # block clears the override back to the env defaults
            self.handler.slo.set_objective(raw, q.get("slo"))
            # declared partitioning feeds the join planner's colocation
            # check over the same poll (absent block clears it)
            p = q.get("partitioning") or {}
            self.handler.joinplan.partitions.set_partitioning(
                raw, p.get("column"), p.get("numPartitions")
            )
        for stale in set(self.handler.quota.tables()) - quota_raw_names:
            self.handler.quota.set_quota(stale, None)
        # SLO overrides clear on their own inventory: a table with an
        # slo block but no QPS quota never had a quota bucket, so the
        # loop above would never reach it after the table is deleted
        for stale in set(self.handler.slo.objective_tables()) - quota_raw_names:
            self.handler.slo.set_objective(stale, None)
