"""Per-server health tracking: consecutive-failure circuit breaker.

The reference broker routes around bad servers indirectly (Helix drops
a dead instance from the external view); within the heartbeat window a
sick-but-registered server keeps absorbing scatter traffic and turning
queries partial.  This tracker closes that gap on the data plane: every
scatter attempt reports success/failure, and after ``failure_threshold``
consecutive failures the server enters a penalty box (circuit OPEN) for
``penalty_ms``.  While open, routing prefers other replicas.  After the
penalty expires the circuit goes HALF_OPEN: exactly one probe request
is allowed through; its outcome closes or re-opens the circuit.

The control plane feeds the same state machine: a heartbeat-miss →
server-dead transition (``ParticipantGateway``) arrives as
``mark_dead`` via the broker's view/instance listener, forcing the
circuit open without waiting for data-plane failures to accumulate —
one code path for "stop sending there", whether learned from missed
heartbeats or from failed scatters.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

CLOSED = "CLOSED"
OPEN = "OPEN"
HALF_OPEN = "HALF_OPEN"


class _Circuit:
    __slots__ = (
        "state", "consecutive_failures", "opened_at",
        "probe_inflight", "probe_claimed_at",
    )

    def __init__(self) -> None:
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.probe_inflight = False
        self.probe_claimed_at = 0.0


class ServerHealthTracker:
    """Thread-safe circuit breaker map, one circuit per server name.

    ``clock`` is injectable so fault-injection tests can step time
    deterministically instead of sleeping through penalty windows.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        penalty_ms: float = 5_000.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.penalty_ms = penalty_ms
        self._clock = clock or time.monotonic
        self._circuits: Dict[str, _Circuit] = {}
        # servers that are alive but still prewarming their compile
        # working set — routing deprioritizes (never excludes) them
        self._warming: set = set()
        self._lock = threading.Lock()

    def _circuit(self, server: str) -> _Circuit:
        c = self._circuits.get(server)
        if c is None:
            c = self._circuits[server] = _Circuit()
        return c

    # -- data-plane reports -------------------------------------------
    def record_success(self, server: str) -> None:
        with self._lock:
            c = self._circuit(server)
            c.state = CLOSED
            c.consecutive_failures = 0
            c.probe_inflight = False

    def record_failure(self, server: str) -> None:
        with self._lock:
            c = self._circuit(server)
            c.consecutive_failures += 1
            if c.state == HALF_OPEN or c.consecutive_failures >= self.failure_threshold:
                # a failed probe re-opens with a fresh penalty window
                c.state = OPEN
                c.opened_at = self._clock()
                c.probe_inflight = False

    # -- control-plane reports (heartbeat-miss / recovery events) -----
    def mark_dead(self, server: str) -> None:
        """Force the circuit open (controller declared the server dead)."""
        with self._lock:
            c = self._circuit(server)
            c.state = OPEN
            c.opened_at = self._clock()
            c.consecutive_failures = max(
                c.consecutive_failures, self.failure_threshold
            )
            c.probe_inflight = False

    def mark_alive(self, server: str) -> None:
        """Controller saw the server again: close immediately (the
        re-registration already proved liveness, no probe needed)."""
        self.record_success(server)

    # -- routing queries ----------------------------------------------
    def _probe_free(self, c: _Circuit) -> bool:
        """A probe claim is a LEASE, not a permanent mark: if its holder
        vanished without reporting (attempt cancelled at query end, or a
        reply the gather loop never read), the claim expires after one
        penalty window so the server is not quarantined forever."""
        if not c.probe_inflight:
            return True
        if (self._clock() - c.probe_claimed_at) * 1000.0 >= self.penalty_ms:
            c.probe_inflight = False
            return True
        return False

    def is_healthy(self, server: str) -> bool:
        """True when routing should prefer this server (circuit CLOSED,
        or OPEN long enough that a half-open probe is due)."""
        with self._lock:
            c = self._circuits.get(server)
            if c is None or c.state == CLOSED:
                return True
            if c.state == OPEN and (self._clock() - c.opened_at) * 1000.0 >= self.penalty_ms:
                c.state = HALF_OPEN
            if c.state == HALF_OPEN:
                return self._probe_free(c)
            return False

    def allow_request(self, server: str) -> bool:
        """Gate an actual send.  CLOSED always passes; HALF_OPEN passes
        exactly one inflight probe per lease window; OPEN passes nothing
        (callers may still send to an OPEN server when it is the only
        replica)."""
        with self._lock:
            c = self._circuits.get(server)
            if c is None or c.state == CLOSED:
                return True
            if c.state == OPEN and (self._clock() - c.opened_at) * 1000.0 >= self.penalty_ms:
                c.state = HALF_OPEN
            if c.state == HALF_OPEN and self._probe_free(c):
                c.probe_inflight = True
                c.probe_claimed_at = self._clock()
                return True
            return False

    # -- warm-start readiness -----------------------------------------
    def set_warming(self, server: str, warming: bool) -> None:
        with self._lock:
            if warming:
                self._warming.add(server)
            else:
                self._warming.discard(server)

    def set_warming_servers(self, servers) -> None:
        """Replace the warming set wholesale (clusterstate refresh)."""
        with self._lock:
            self._warming = set(servers)

    def is_warming(self, server: str) -> bool:
        with self._lock:
            return server in self._warming

    def warming_servers(self) -> set:
        with self._lock:
            return set(self._warming)

    def state_of(self, server: str) -> str:
        with self._lock:
            c = self._circuits.get(server)
            return c.state if c is not None else CLOSED

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Dashboard/metrics view of every tracked circuit."""
        with self._lock:
            return {
                name: {
                    "state": c.state,
                    "consecutiveFailures": c.consecutive_failures,
                }
                for name, c in self._circuits.items()
            }
