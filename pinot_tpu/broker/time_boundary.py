"""Time boundary service for hybrid tables.

Reference: ``HelixExternalViewBasedTimeBoundaryService.java:36`` — for a
hybrid table the boundary is the max end-time over the OFFLINE table's
segments; the broker rewrites the offline sub-query to ``time <=
boundary`` and the realtime one to ``time > boundary`` so rows are
counted exactly once across the two sides.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

from pinot_tpu.segment.immutable import SegmentMetadata


def compute_boundary(
    segment_metas: Iterable[SegmentMetadata],
) -> Optional[Tuple[str, int]]:
    """(time column, max end time) over the offline segments, or None —
    the single definition of the hybrid boundary rule, shared by the
    in-process listener path and the networked cluster-state snapshot."""
    col: Optional[str] = None
    max_end: Optional[int] = None
    for meta in segment_metas:
        if meta.time_column is None or meta.end_time is None:
            continue
        col = meta.time_column
        max_end = meta.end_time if max_end is None else max(max_end, meta.end_time)
    if col is None or max_end is None:
        return None
    return (col, max_end)


class TimeBoundaryService:
    def __init__(self) -> None:
        self._boundaries: Dict[str, Tuple[str, int]] = {}
        self._lock = threading.Lock()

    def update_from_segments(
        self, offline_table: str, segment_metas: Iterable[SegmentMetadata]
    ) -> None:
        boundary = compute_boundary(segment_metas)
        if boundary is not None:
            with self._lock:
                self._boundaries[offline_table] = boundary

    def set(self, offline_table: str, column: str, value: int) -> None:
        with self._lock:
            self._boundaries[offline_table] = (column, value)

    def get(self, offline_table: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._boundaries.get(offline_table)

    def remove(self, offline_table: str) -> None:
        with self._lock:
            self._boundaries.pop(offline_table, None)
