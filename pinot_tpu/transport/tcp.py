"""TCP data-plane transport with 4-byte length framing.

The reference's data plane is Netty TCP with a
``LengthFieldBasedFrameDecoder``/``Prepender`` (4-byte prefix,
``NettyTCPServer.java:93-94``) and async keyed connection pools
(``transport/pool/AsyncPoolImpl.java``).  The equivalent here:
threaded socket server + per-server blocking-socket pools, with the
broker fanning requests out on a thread pool (``scatter_gather.py``).
Queries between processes ride this; the heavy lifting (the query
itself) is on-device, so the transport's job is framing, pooling,
timeouts, and failure isolation.
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

MAX_FRAME = 1 << 30


logger = logging.getLogger(__name__)


class TransportError(Exception):
    pass


_BIG_FRAME = 1 << 16


def send_frame(sock: socket.socket, payload: bytes) -> None:
    if len(payload) < _BIG_FRAME:
        sock.sendall(struct.pack(">I", len(payload)) + payload)
    else:
        # large frames (columnar ingest blocks, shuffle exchanges):
        # never concat-copy megabytes just to prepend 4 bytes — two
        # sendalls cost one extra syscall, not an extra full copy
        sock.sendall(struct.pack(">I", len(payload)))
        sock.sendall(payload)


def recv_frame(sock: socket.socket) -> bytes:
    header = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", header)
    if n > MAX_FRAME:
        raise TransportError(f"frame too large: {n}")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # recv_into a preallocated buffer: no per-chunk append/copy churn
    # on multi-megabyte frames (columnar ingest blocks)
    buf = bytearray(n)
    view = memoryview(buf)
    pos = 0
    while pos < n:
        got = sock.recv_into(view[pos:], n - pos)
        if not got:
            raise TransportError("connection closed")
        pos += got
    return bytes(buf)


class TcpServer:
    """Length-framed request/response server; one thread per connection
    (the NettyServer.RequestHandler analog, ``NettyServer.java:80``)."""

    def __init__(self, handler: Callable[[bytes], bytes], host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._threads: List[threading.Thread] = []

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def start(self) -> None:
        self._running = True
        self._sock.listen(64)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError as e:
                if self._running:
                    # transient failure (e.g. EMFILE under fd pressure)
                    # must not kill the accept loop — only shutdown does
                    logger.warning("accept failed on %s: %s", self.address, e)
                    import time as _time

                    _time.sleep(0.05)
                    continue
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while self._running:
                try:
                    payload = recv_frame(conn)
                except TransportError:
                    return
                try:
                    reply = self.handler(payload)
                except Exception as e:  # handler errors must not kill the conn
                    reply = b"ERR:" + str(e).encode("utf-8", "replace")
                send_frame(conn, reply)
        finally:
            conn.close()

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class _Pool:
    """Blocking-socket pool for one server (KeyedPoolImpl analog)."""

    def __init__(self, address: Tuple[str, int], max_size: int = 8):
        self.address = address
        self.max_size = max_size
        self._idle: List[socket.socket] = []
        self._lock = threading.Lock()

    def checkout(self, timeout: float) -> socket.socket:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        sock = socket.create_connection(self.address, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if len(self._idle) < self.max_size:
                self._idle.append(sock)
                return
        sock.close()

    def destroy(self, sock: socket.socket) -> None:
        try:
            sock.close()
        except OSError:
            pass


class TcpTransport:
    """Client side: pooled request/response to named servers."""

    def __init__(self) -> None:
        self._pools: Dict[Tuple[str, int], _Pool] = {}
        self._lock = threading.Lock()

    def _pool(self, address: Tuple[str, int]) -> _Pool:
        with self._lock:
            pool = self._pools.get(address)
            if pool is None:
                pool = _Pool(address)
                self._pools[address] = pool
            return pool

    def request(self, address: Tuple[str, int], payload: bytes, timeout: float = 15.0) -> bytes:
        pool = self._pool(address)
        sock = pool.checkout(timeout)
        try:
            sock.settimeout(timeout)
            send_frame(sock, payload)
            reply = recv_frame(sock)
        except (OSError, TransportError) as e:
            pool.destroy(sock)
            raise TransportError(str(e)) from e
        pool.checkin(sock)
        if reply[:4] == b"ERR:":
            raise TransportError(reply[4:].decode("utf-8", "replace"))
        return reply
