from pinot_tpu.transport.tcp import TcpServer, TcpTransport
from pinot_tpu.transport.local import LocalTransport

__all__ = ["TcpServer", "TcpTransport", "LocalTransport"]
