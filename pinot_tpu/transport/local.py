"""In-process transport: direct handler dispatch.

Used by the single-process quickstart and the in-process cluster tests
(the reference's integration tests also run all roles in one JVM,
``PerfBenchmarkDriver.java:160-162``); same interface as TcpTransport so
broker code is transport-agnostic.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

from pinot_tpu.transport.tcp import TransportError


class LocalTransport:
    def __init__(self) -> None:
        self._handlers: Dict[Tuple[str, int], Callable[[bytes], bytes]] = {}
        self._lock = threading.Lock()
        self._down: set = set()

    def register(self, address: Tuple[str, int], handler: Callable[[bytes], bytes]) -> None:
        with self._lock:
            self._handlers[address] = handler

    def set_down(self, address: Tuple[str, int], down: bool = True) -> None:
        """Simulate a dead server (for partial-failure tests)."""
        with self._lock:
            if down:
                self._down.add(address)
            else:
                self._down.discard(address)

    def request(self, address: Tuple[str, int], payload: bytes, timeout: float = 15.0) -> bytes:
        with self._lock:
            if address in self._down:
                raise TransportError(f"server {address} unreachable")
            handler = self._handlers.get(address)
        if handler is None:
            raise TransportError(f"no handler at {address}")
        reply = handler(payload)
        if reply[:4] == b"ERR:":
            raise TransportError(reply[4:].decode("utf-8", "replace"))
        return reply
