"""pinot_tpu — a TPU-native realtime distributed OLAP datastore.

A ground-up rebuild of the capabilities of LinkedIn Pinot v0.016
(reference mounted at /root/reference) designed TPU-first:

- columnar immutable segments staged into HBM as packed device arrays
  (reference: pinot-core/.../segment/, PinotDataBuffer mmap buffers)
- per-segment query execution (filter -> project -> aggregate/group-by)
  as jit-compiled XLA kernels instead of a virtual-call operator tree
  (reference: pinot-core/.../core/operator/)
- segment parallelism via a leading segment axis sharded over a
  `jax.sharding.Mesh`, with `psum`-style collectives replacing both the
  intra-server MCombineOperator thread pools and most of the broker's
  scatter-gather reduce (reference: MCombineOperator.java,
  BrokerReduceService.java)
- a host-side control plane (controller / broker / server roles) with
  ideal-state vs observed-state semantics mirroring Helix
  (reference: pinot-controller/.../PinotHelixResourceManager.java)

Package layout:
  common/    schema, table config, request/response model, DataTable wire format
  pql/       PQL parser + filter-tree optimizer
  segment/   segment build (two-pass), on-disk format, loader, device staging
  engine/    the TPU query engine: predicate -> mask kernels, aggregation,
             group-by scatter-add, selection top-k, per-segment executor
  parallel/  multi-segment stacking + shard_map multi-chip execution
  startree/  star-tree pre-aggregation
  realtime/  mutable segments, stream providers, LLC-style commit FSM
  controller/ broker/ server/ transport/  cluster topology
  tools/     scan-based reference oracle, quickstarts, data generators, perf
  utils/     metrics, tracing, retry
"""

__version__ = "0.1.0"
